"""Time-window compaction with TTL expiry.

Reference behavior: src/storage/src/compaction/ — `SimplePicker` selects a
region's L0 files and expired files (TTL, picker.rs:57-90);
`SimpleTimeWindowStrategy` buckets them by an inferred time window
(strategy.rs:36-120); `CompactionTaskImpl` merges each bucket through the
region's reader into L1 outputs and commits one RegionEdit.

TPU-first deltas: inputs are read as SoA columns and merged with the
sort-based merge/dedup kernel twin (one lexsort + keep-mask — the same
algorithm the device scan path uses) instead of the reference's heap-based
k-way MergeReader; each time-window bucket is written as one L1 Parquet
file whose rows stay (series, ts, seq)-sorted so scans and the device
kernels consume them directly.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..ops.kernels import merge_dedup_numpy
from .sst import FileMeta

logger = logging.getLogger(__name__)

# window candidates (seconds), smallest that covers the span is chosen
# (reference: strategy.rs TIME_BUCKETS)
TIME_BUCKETS_S = [3600, 2 * 3600, 12 * 3600, 24 * 3600, 7 * 24 * 3600]


def infer_time_bucket_ms(span_ms: int) -> int:
    for b in TIME_BUCKETS_S:
        if span_ms <= b * 1000:
            return b * 1000
    return TIME_BUCKETS_S[-1] * 1000


@dataclass
class CompactionPlan:
    inputs: List[FileMeta]            # files merged into L1
    expired: List[FileMeta]           # dropped wholesale (TTL)
    window_ms: int


def pick_compaction(ssts, *, ttl_ms: Optional[int] = None,
                    now_ms: Optional[int] = None,
                    min_l0_files: int = 1,
                    time_window_ms: Optional[int] = None
                    ) -> Optional[CompactionPlan]:
    """Select L0 files (and TTL-expired files at any level) for one
    compaction run. Returns None when there is nothing to do."""
    now_ms = int(time.time() * 1000) if now_ms is None else now_ms
    expired: List[FileMeta] = []
    if ttl_ms is not None:
        cutoff = now_ms - ttl_ms
        expired = [f for f in ssts.all_files() if f.time_range[1] < cutoff]
    expired_names = {f.file_name for f in expired}
    l0 = [f for f in ssts.levels[0] if f.file_name not in expired_names]
    if len(l0) < min_l0_files and not expired:
        return None
    if not l0 and not expired:
        return None
    window = time_window_ms
    if window is None:
        if l0:
            lo = min(f.time_range[0] for f in l0)
            hi = max(f.time_range[1] for f in l0)
            window = infer_time_bucket_ms(hi - lo + 1)
        else:
            window = TIME_BUCKETS_S[0] * 1000
    return CompactionPlan(inputs=l0, expired=expired, window_ms=window)


def run_compaction(region, plan: CompactionPlan,
                   *, ttl_ms: Optional[int] = None,
                   now_ms: Optional[int] = None) -> List[FileMeta]:
    """Merge the plan's input files into per-window L1 SSTs and commit the
    edit. Returns the new files. Safe to run while writes continue: inputs
    are immutable SSTs; the version/manifest swap happens under the region
    writer lock."""
    if not plan.inputs and not plan.expired:
        return []
    from ..common import background_jobs
    from ..common.telemetry import increment_counter, span, timer
    with background_jobs.job("compaction", region=region.name,
                             inputs=len(plan.inputs),
                             expired=len(plan.expired)), \
            span("compaction", region=region.name,
                 inputs=len(plan.inputs), expired=len(plan.expired)), \
            timer("compaction"):
        out = _run_compaction_inner(region, plan, ttl_ms=ttl_ms,
                                    now_ms=now_ms)
    increment_counter("compaction_runs")
    increment_counter("compaction_files_in", len(plan.inputs))
    increment_counter("compaction_files_out", len(out))
    return out


def _run_compaction_inner(region, plan: CompactionPlan,
                          *, ttl_ms: Optional[int] = None,
                          now_ms: Optional[int] = None) -> List[FileMeta]:
    now_ms = int(time.time() * 1000) if now_ms is None else now_ms
    al = region.access_layer
    schema = region.schema
    field_names = [c.name for c in schema.field_columns()]

    # trivial move (RocksDB-style): time-disjoint L0 files cannot hold
    # competing versions of any (series, ts) key, so re-levelling them is
    # a metadata-only edit — no read, no merge, no rewrite. This is the
    # common case for in-order telemetry (every flush/bulk-load covers a
    # fresh window) and keeps sustained ingest from paying a full region
    # rewrite every max_l0_files batches.
    if plan.inputs and not plan.expired and ttl_ms is None:
        from dataclasses import replace as _dc_replace
        by_lo = sorted(plan.inputs, key=lambda f: f.time_range[0])
        disjoint = all(
            not by_lo[i].keys_overlap(by_lo[j])
            for i in range(len(by_lo)) for j in range(i + 1, len(by_lo)))
        if disjoint:
            moved = [_dc_replace(f, level=1) for f in by_lo]
            region.commit_compaction(
                removed=[f.file_name for f in by_lo], added=moved,
                purge=False)
            logger.info("region %s trivially moved %d disjoint L0 files "
                        "to L1", region.name, len(moved))
            return moved

    retracts = bool(plan.expired)
    new_files: List[FileMeta] = []
    if plan.inputs:
        # overlap input decode: parquet reads drop the GIL, so concurrent
        # readers hide IO + decompression behind each other (reference's
        # parallel compaction readers, strategy.rs:36-120)
        from ..common.runtime import parallel_map
        datas = [d for d in parallel_map(al.read_sst, plan.inputs)
                 if d.num_rows]
        if datas:
            sids = np.concatenate([d.series_ids for d in datas])
            ts = np.concatenate([d.ts for d in datas])
            seq = np.concatenate([d.seq for d in datas])
            op = np.concatenate([d.op_types for d in datas])
            fields: Dict[str, Tuple[np.ndarray, Optional[np.ndarray]]] = {}
            for name in field_names:
                cols = [d.fields[name] for d in datas]
                data = np.concatenate([c[0] for c in cols])
                if any(c[1] is not None for c in cols):
                    valid = np.concatenate([
                        c[1] if c[1] is not None
                        else np.ones(len(c[0]), dtype=bool) for c in cols])
                else:
                    valid = None
                fields[name] = (data, valid)
            # L1 collapses MVCC history: keep the newest row per (series, ts)
            # (delete tombstones survive as rows — older L1 files may still
            # hold versions of the key they must shadow)
            kept = merge_dedup_numpy(sids, ts, seq, op, keep_deletes=True)
            sids, ts, seq, op = sids[kept], ts[kept], seq[kept], op[kept]
            fields = {n: (d[kept], v[kept] if v is not None else None)
                      for n, (d, v) in fields.items()}
            if ttl_ms is not None:
                live = ts >= (now_ms - ttl_ms)
                if not live.all():
                    retracts = True
                    sids, ts, seq, op = (a[live] for a in (sids, ts, seq, op))
                    fields = {n: (d[live], v[live] if v is not None else None)
                              for n, (d, v) in fields.items()}
            if len(ts):
                # bucket rows by time window → one sorted L1 file per bucket;
                # encode+write buckets concurrently (zstd/parquet encode
                # drops the GIL) so output IO overlaps encoding
                buckets = ts // plan.window_ms

                def _write_bucket(b):
                    m = buckets == b
                    bs, bt, bq, bo = sids[m], ts[m], seq[m], op[m]
                    bf = {n: (d[m], v[m] if v is not None else None)
                          for n, (d, v) in fields.items()}
                    tag_cols = {
                        name: region.series_dict.decode_tag_column(bs, i)
                        for i, name in
                        enumerate(region.series_dict.tag_names)}
                    return al.write_sst(level=1, series_ids=bs, ts=bt,
                                        seq=bq, op_types=bo, fields=bf,
                                        tag_columns=tag_cols, schema=schema)

                from ..common.runtime import parallel_map
                metas = parallel_map(_write_bucket, np.unique(buckets))
                new_files.extend(m for m in metas if m is not None)

    removed = [f.file_name for f in plan.inputs] + \
        [f.file_name for f in plan.expired]
    region.commit_compaction(removed=removed, added=new_files,
                             retracts=retracts)
    logger.info("region %s compacted %d inputs (+%d expired) -> %d L1 files",
                region.name, len(plan.inputs), len(plan.expired),
                len(new_files))
    return new_files
