"""Write-ahead log: segmented, checksummed, per-region append log.

Reference behavior: src/log-store/src/raft_engine/log_store.rs +
src/storage/src/wal.rs — per-region namespaces, append(seq, payload),
read_from(seq) for replay, obsolete(seq) truncation after flush. Host-side
only; the accelerator never sees the WAL.

Format: segment files `{first_seq:020d}.wal`, each a sequence of records:
    [len u32][crc32 u32][seq u64][schema_version u32][payload]
Records are append-only; fsync policy is configurable (group commit happens
at the region writer level by batching mutations into one WriteBatch).
"""

from __future__ import annotations

import logging
import os
import struct
import threading
import zlib
from typing import Iterator, List, Optional, Tuple

from ..common import failpoint as _fp
from ..common.locks import TrackedLock
from ..errors import StorageError

logger = logging.getLogger(__name__)

_REC_HDR = struct.Struct("<IIQI")  # len, crc, seq, schema_version

_fp.register("wal_append")
_fp.register("wal_append_torn")
_fp.register("wal_fsync")


class Wal:
    """WAL for one region, stored under `dir`."""

    SEGMENT_BYTES = 64 * 1024 * 1024

    def __init__(self, dir_path: str, *, sync_on_write: bool = False,
                 segment_bytes: Optional[int] = None):
        self.dir = dir_path
        self.sync_on_write = sync_on_write
        self.segment_bytes = segment_bytes or self.SEGMENT_BYTES
        os.makedirs(self.dir, exist_ok=True)
        self._lock = TrackedLock("storage.wal")
        self._fh = None
        self._fh_path: Optional[str] = None
        self._fh_size = 0
        # set when an injected torn write left garbage at the tail of the
        # OPEN segment and the process survived (the torture rig abandons
        # the object; a live server does not) — the next append must cut
        # the garbage off before writing or it would bury later acked
        # records behind bytes replay cannot cross
        self._fh_dirty_tail = False

    # ---- segments ----
    def _segments(self) -> List[Tuple[int, str]]:
        segs = []
        for fn in os.listdir(self.dir):
            if fn.endswith(".wal"):
                try:
                    segs.append((int(fn[:-4]), os.path.join(self.dir, fn)))
                except ValueError:
                    continue
        segs.sort()
        return segs

    def _open_segment(self, first_seq: int) -> None:
        if self._fh is not None:
            self._fh.close()
        path = os.path.join(self.dir, f"{first_seq:020d}.wal")
        self._fh = open(path, "ab")
        self._fh_path = path
        self._fh_size = self._fh.tell()

    # ---- api ----
    def append(self, seq: int, payload: bytes, schema_version: int = 0) -> None:
        with self._lock:
            _fp.fail_point("wal_append")
            if self._fh is not None and self._fh_dirty_tail:
                # in-process recovery from an injected torn write: drop
                # the garbage (_fh_size never advanced past it) so this
                # record lands replayable. Runs BEFORE the rotation check
                # so a full segment can never rotate away with garbage
                # buried mid-log.
                self._fh.truncate(self._fh_size)
                self._fh.flush()
                self._fh_dirty_tail = False
            if self._fh is None or self._fh_size >= self.segment_bytes:
                self._open_segment(seq)
            crc = zlib.crc32(payload)
            rec = _REC_HDR.pack(len(payload), crc, seq, schema_version) + payload
            if _fp.fires("wal_append_torn"):
                # crash mid-append: half the record reaches the file —
                # recovery must truncate it away and keep earlier records
                self._fh.write(rec[:max(1, len(rec) // 2)])
                self._fh.flush()
                self._fh_dirty_tail = True
                raise _fp.SimulatedCrash("wal_append_torn")
            self._fh.write(rec)
            self._fh.flush()
            # account the record before the fsync: it is in the file now,
            # so a failed fsync must not leave segment rotation blind to it
            self._fh_size += len(rec)
            if self.sync_on_write:
                from ..common.telemetry import timer
                _fp.fail_point("wal_fsync")
                with timer("wal_fsync"):
                    os.fsync(self._fh.fileno())
            from ..common.telemetry import increment_counter
            increment_counter("wal_bytes", len(rec))

    def sync(self) -> None:
        with self._lock:
            if self._fh is not None:
                from ..common.telemetry import timer
                self._fh.flush()
                with timer("wal_fsync"):
                    os.fsync(self._fh.fileno())

    def read_from(self, start_seq: int) -> Iterator[Tuple[int, int, bytes]]:
        """Yield (seq, schema_version, payload) for all records with
        seq >= start_seq.

        A torn/corrupt record in the FINAL segment is a crash mid-append:
        the scan terminates cleanly AND the segment is truncated at the
        last good record (with a WARN) so later appends never land past
        the garbage — without the truncate, append-mode writes would bury
        the torn bytes mid-segment and brick the next replay. The same in
        an EARLIER segment means acknowledged writes were lost (bit rot) —
        replay aborts with StorageError rather than silently skipping to
        newer segments. Each record carries a CRC32 over its payload, so a
        corrupt-but-complete record is detected, never silently replayed."""
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
            segs = self._segments()
        for i, (first, path) in enumerate(segs):
            # skip whole segments below start_seq (next segment's first seq
            # bounds this one's contents)
            if i + 1 < len(segs) and segs[i + 1][0] <= start_seq:
                continue
            records, clean, good_pos = self._read_segment(path, start_seq)
            yield from records
            if not clean:
                if i + 1 < len(segs):
                    raise StorageError(
                        f"corrupt WAL record mid-log in {path}; refusing to "
                        f"replay past the gap")
                self._repair_torn_tail(path, good_pos)
                return  # torn tail of the active segment: normal crash

    def _repair_torn_tail(self, path: str, good_pos: int) -> None:
        """Drop a torn/corrupt tail record left by a crash mid-append."""
        with self._lock:
            if self._fh is not None and self._fh_path == path:
                return  # segment reopened for appends already; leave it
            try:
                size = os.path.getsize(path)
                logger.warning(
                    "wal %s: torn/corrupt tail record; truncating %d bytes "
                    "at offset %d (crash mid-append)", path,
                    size - good_pos, good_pos)
                with open(path, "rb+") as f:
                    f.truncate(good_pos)
                    os.fsync(f.fileno())
            except OSError as e:  # pragma: no cover
                raise StorageError(f"wal tail repair failed: {e}", cause=e)

    def _read_segment(self, path: str, start_seq: int
                      ) -> Tuple[List[Tuple[int, int, bytes]], bool, int]:
        """Returns (records >= start_seq, clean, offset past the last good
        record) — the offset is the truncation point on a torn tail."""
        try:
            with open(path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return [], True, 0
        out: List[Tuple[int, int, bytes]] = []
        pos = 0
        n = len(data)
        while pos + _REC_HDR.size <= n:
            ln, crc, seq, sv = _REC_HDR.unpack_from(data, pos)
            body_start = pos + _REC_HDR.size
            if body_start + ln > n:
                return out, False, pos  # torn record
            payload = data[body_start:body_start + ln]
            if zlib.crc32(payload) != crc:
                return out, False, pos  # corrupt record
            pos = body_start + ln
            if seq >= start_seq:
                out.append((seq, sv, payload))
        return out, pos == n, pos

    def obsolete(self, seq: int) -> None:
        """Delete segments whose entire contents are <= seq."""
        with self._lock:
            segs = self._segments()
            # a segment can be deleted if the NEXT segment starts at <= seq+1,
            # meaning every record in it has seq <= that bound.
            for i, (first, path) in enumerate(segs):
                if i + 1 < len(segs) and segs[i + 1][0] <= seq + 1:
                    if self._fh_path == path and self._fh is not None:
                        continue  # never delete the active segment
                    try:
                        os.unlink(path)
                    except OSError as e:  # pragma: no cover
                        raise StorageError(f"wal gc failed: {e}", cause=e)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                os.fsync(self._fh.fileno())
                self._fh.close()
                self._fh = None


class NoopWal(Wal):
    """WAL-less mode for tests/benchmarks (reference: src/log-store/src/noop.rs)."""

    def __init__(self):  # noqa: super-init-not-called
        self._lock = TrackedLock("storage.wal")

    def append(self, seq, payload, schema_version=0):
        pass

    def sync(self):
        pass

    def read_from(self, start_seq):
        return iter(())

    def obsolete(self, seq):
        pass

    def close(self):
        pass
