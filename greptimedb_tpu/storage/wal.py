"""Write-ahead log: segmented, checksummed, per-region append log.

Reference behavior: src/log-store/src/raft_engine/log_store.rs +
src/storage/src/wal.rs — per-region namespaces, append(seq, payload),
read_from(seq) for replay, obsolete(seq) truncation after flush. Host-side
only; the accelerator never sees the WAL.

Format: segment files `{first_seq:020d}.wal`, each a sequence of records:
    [len u32][crc32 u32][seq u64][schema_version u32][payload]
Records are append-only; fsync policy is configurable (group commit happens
at the region writer level by batching mutations into one WriteBatch).
"""

from __future__ import annotations

import logging
import os
import struct
import threading
import time
import zlib
from typing import Iterator, List, Optional, Tuple

from ..common import failpoint as _fp
from ..common.locks import TrackedLock
# hoisted to module scope: `append` runs per region write — a function-
# local import on the hot loop re-resolves sys.modules every call
# (matching every other storage module)
from ..common.telemetry import increment_counter, timer
from ..errors import StorageError

logger = logging.getLogger(__name__)

_REC_HDR = struct.Struct("<IIQI")  # len, crc, seq, schema_version

_fp.register("wal_append")
_fp.register("wal_append_torn")
_fp.register("wal_fsync")
#: crash window between a cohort member's record write and the shared
#: group-commit fsync: at most the (unacked) cohort may be lost, never
#: an acked row (tests/torture.py drives it)
_fp.register("wal_group_commit")


# ---------------------------------------------------------------------------
# group commit configuration (process-wide; SET wal_group_commit /
# wal_group_max_wait_us / wal_group_max_batch and the matching
# GREPTIME_WAL_GROUP_* env knobs route here)
# ---------------------------------------------------------------------------

from ..utils import env_flag as _env_flag, env_int as _env_int

#: one-element lists so SET mutates in place without rebinding (the
#: pattern telemetry/runtime knobs use; greptlint GL08 wants the
#: mutation behind a lock — these are single-slot swaps guarded below)
_GC_LOCK = TrackedLock("storage.wal_group_config")
#: max_wait_us defaults to 0 — pure fsync chaining: the cohort is
#: whatever piled up while the previous fsync was in flight, so group
#: commit never ADDS latency on a fast device; a positive window only
#: pays off when fsync is expensive relative to the OS sleep quantum
_GC_ENABLED = [_env_flag("GREPTIME_WAL_GROUP_COMMIT", True)]
_GC_MAX_WAIT_US = [_env_int("GREPTIME_WAL_GROUP_MAX_WAIT_US", 0)]
_GC_MAX_BATCH = [_env_int("GREPTIME_WAL_GROUP_MAX_BATCH", 128)]
#: hard bound on how long a cohort member parks for the shared fsync
#: before surfacing a storage error (never deadlock on a dead leader)
_GC_WAIT_TIMEOUT_S = 30.0


def configure_group_commit(*, enabled: Optional[bool] = None,
                           max_wait_us: Optional[int] = None,
                           max_batch: Optional[int] = None) -> None:
    """Process-wide group-commit knobs (SET wal_group_commit & co)."""
    with _GC_LOCK:
        if enabled is not None:
            _GC_ENABLED[0] = bool(enabled)
        if max_wait_us is not None:
            if max_wait_us < 0:
                raise ValueError("wal_group_max_wait_us must be >= 0")
            _GC_MAX_WAIT_US[0] = int(max_wait_us)
        if max_batch is not None:
            if max_batch < 1:
                raise ValueError("wal_group_max_batch must be >= 1")
            _GC_MAX_BATCH[0] = int(max_batch)


def group_commit_enabled() -> bool:
    return _GC_ENABLED[0]


def group_commit_settings() -> Tuple[bool, int, int]:
    """(enabled, max_wait_us, max_batch) — one consistent read."""
    with _GC_LOCK:
        return _GC_ENABLED[0], _GC_MAX_WAIT_US[0], _GC_MAX_BATCH[0]


class Wal:
    """WAL for one region, stored under `dir`."""

    SEGMENT_BYTES = 64 * 1024 * 1024

    def __init__(self, dir_path: str, *, sync_on_write: bool = False,
                 segment_bytes: Optional[int] = None):
        self.dir = dir_path
        self.sync_on_write = sync_on_write
        self.segment_bytes = segment_bytes or self.SEGMENT_BYTES
        os.makedirs(self.dir, exist_ok=True)
        self._lock = TrackedLock("storage.wal")
        self._fh = None
        self._fh_path: Optional[str] = None
        self._fh_size = 0
        # ---- group-commit cohort state (all under _gc_cond's lock) ----
        # tickets count records written to the OS; the leader's fsync
        # covers every ticket <= the value it sampled under _lock, so a
        # waiter is durable once _synced_ticket reaches its own ticket.
        self._gc_cond = threading.Condition(
            TrackedLock("storage.wal_group"))
        self._written_ticket = 0      # bumped under _lock per record
        self._synced_ticket = 0       # highest ticket a good fsync covers
        self._failed_ticket = 0       # highest ticket a failed fsync hit
        self._sync_exc: Optional[BaseException] = None
        self._leader_active = False
        # set when an injected torn write left garbage at the tail of the
        # OPEN segment and the process survived (the torture rig abandons
        # the object; a live server does not) — the next append must cut
        # the garbage off before writing or it would bury later acked
        # records behind bytes replay cannot cross
        self._fh_dirty_tail = False

    # ---- segments ----
    def _segments(self) -> List[Tuple[int, str]]:
        segs = []
        for fn in os.listdir(self.dir):
            if fn.endswith(".wal"):
                try:
                    segs.append((int(fn[:-4]), os.path.join(self.dir, fn)))
                except ValueError:
                    continue
        segs.sort()
        return segs

    def _open_segment(self, first_seq: int) -> None:
        if self._fh is not None:
            if self.sync_on_write:
                # group commit fsyncs OUTSIDE the WAL lock against the
                # current fd only: a rotation must not close a segment
                # carrying cohort records that never saw an fsync (in
                # per-append mode this re-syncs already-durable bytes
                # once per 64 MiB — noise)
                self._fh.flush()
                os.fsync(self._fh.fileno())
            self._fh.close()
        path = os.path.join(self.dir, f"{first_seq:020d}.wal")
        self._fh = open(path, "ab")
        self._fh_path = path
        self._fh_size = self._fh.tell()

    # ---- api ----
    def group_commit_active(self) -> bool:
        """True when this WAL's durability waits should ride the shared
        group-commit fsync (the region writer then appends under its
        lock and parks OUTSIDE it, so concurrent writers overlap)."""
        return self.sync_on_write and group_commit_enabled()

    def append(self, seq: int, payload: bytes, schema_version: int = 0) -> None:
        """Write one record; when `sync_on_write`, return only after an
        fsync covers it — per-append (group commit off) or shared
        (group commit on)."""
        group = self.group_commit_active()
        ticket = self._append_locked(
            seq, payload, schema_version,
            inline_sync=self.sync_on_write and not group)
        if group:
            self.wait_durable(ticket)

    def append_async(self, seq: int, payload: bytes,
                     schema_version: int = 0) -> int:
        """Write one record WITHOUT waiting for durability; returns the
        commit ticket to pass to :meth:`wait_durable`. The region writer
        uses this under its writer lock so the (slow) fsync wait happens
        after the lock is released."""
        return self._append_locked(seq, payload, schema_version,
                                   inline_sync=False)

    def _append_locked(self, seq: int, payload: bytes, schema_version: int,
                       *, inline_sync: bool) -> int:
        with self._lock:
            _fp.fail_point("wal_append")
            if self._fh is not None and self._fh_dirty_tail:
                # in-process recovery from an injected torn write: drop
                # the garbage (_fh_size never advanced past it) so this
                # record lands replayable. Runs BEFORE the rotation check
                # so a full segment can never rotate away with garbage
                # buried mid-log.
                self._fh.truncate(self._fh_size)
                self._fh.flush()
                self._fh_dirty_tail = False
            if self._fh is None or self._fh_size >= self.segment_bytes:
                self._open_segment(seq)
            crc = zlib.crc32(payload)
            rec = _REC_HDR.pack(len(payload), crc, seq, schema_version) + payload
            if _fp.fires("wal_append_torn"):
                # crash mid-append: half the record reaches the file —
                # recovery must truncate it away and keep earlier records
                self._fh.write(rec[:max(1, len(rec) // 2)])
                self._fh.flush()
                self._fh_dirty_tail = True
                raise _fp.SimulatedCrash("wal_append_torn")
            self._fh.write(rec)
            self._fh.flush()
            # account the record before the fsync: it is in the file now,
            # so a failed fsync must not leave segment rotation blind to it
            self._fh_size += len(rec)
            self._written_ticket += 1
            ticket = self._written_ticket
            if inline_sync:
                _fp.fail_point("wal_fsync")
                with timer("wal_fsync"):
                    os.fsync(self._fh.fileno())
            increment_counter("wal_bytes", len(rec))
        return ticket

    # ---- group commit ----
    def wait_durable(self, ticket: int) -> None:
        """Park until a shared fsync covers `ticket`. The first waiter of
        a cohort elects itself leader, batches the flush+fsync, and wakes
        everyone; followers re-check on a bounded wait so a dead leader
        (or a KILL on the waiting statement) can never wedge the cohort."""
        from ..common.process_list import check_cancelled
        _fp.fail_point("wal_group_commit")
        deadline = time.monotonic() + _GC_WAIT_TIMEOUT_S
        while True:
            lead = False
            with self._gc_cond:
                if self._synced_ticket >= ticket:
                    return                     # a shared fsync covered us
                if self._failed_ticket >= ticket:
                    raise StorageError(
                        f"wal group fsync failed for ticket {ticket}: "
                        f"{self._sync_exc}", cause=self._sync_exc
                        if isinstance(self._sync_exc, Exception) else None)
                if not self._leader_active:
                    self._leader_active = True
                    lead = True
                else:
                    self._gc_cond.wait(timeout=0.05)
            if lead:
                self._lead_sync()              # re-loop to check coverage
                continue
            check_cancelled()                  # killed mid-wait: bail out
            if time.monotonic() > deadline:
                raise StorageError(
                    f"wal group commit wait timed out after "
                    f"{_GC_WAIT_TIMEOUT_S:.0f}s (ticket {ticket})")

    def _lead_sync(self) -> None:
        """Leader duties: give the cohort a short window to pile on, then
        pay ONE fsync for every record written so far and publish the
        covered ticket. Any fsync failure (or injected crash) is recorded
        for the cohort and re-raised in the leader's own thread.

        The flush serves a whole cohort, so it roots its own trace +
        background_jobs entry (common/background_jobs) rather than
        riding whichever writer happened to get elected."""
        from ..common import background_jobs
        with background_jobs.job("wal_group_commit",
                                 region=os.path.basename(self.dir)):
            self._lead_sync_inner()

    def _lead_sync_inner(self) -> None:
        _enabled, max_wait_us, max_batch = group_commit_settings()
        if max_wait_us > 0:
            with self._gc_cond:
                backlog = self._written_ticket - self._synced_ticket
            if backlog < max_batch:
                # the accumulation window — bounded, microseconds-scale
                time.sleep(max_wait_us / 1e6)
        target = 0
        try:
            dup_fd = -1
            with self._lock:
                target = self._written_ticket
                if self._fh is not None and target > self._synced_ticket:
                    # flush userspace buffers under the lock, then fsync
                    # a dup'd fd OUTSIDE it: the whole point of group
                    # commit is that appends keep landing while the
                    # device syncs (the dup survives a concurrent
                    # rotation, and rotation itself fsyncs the old
                    # segment before closing it — see _open_segment)
                    self._fh.flush()
                    dup_fd = os.dup(self._fh.fileno())
            if dup_fd >= 0:
                try:
                    _fp.fail_point("wal_fsync")
                    with timer("wal_fsync"):
                        os.fsync(dup_fd)
                finally:
                    os.close(dup_fd)
        except BaseException as e:
            # the cohort (including this thread's own caller) must see
            # the failure; the ORIGINAL exception propagates here so an
            # injected SimulatedCrash stays a crash in the leader
            with self._gc_cond:
                self._failed_ticket = max(self._failed_ticket,
                                          target or self._written_ticket)
                self._sync_exc = e
                self._leader_active = False
                self._gc_cond.notify_all()
            raise
        with self._gc_cond:
            cohort = target - self._synced_ticket
            self._synced_ticket = max(self._synced_ticket, target)
            self._leader_active = False
            self._gc_cond.notify_all()
        if cohort > 0:
            increment_counter("wal_group_commit_fsyncs")
            increment_counter("wal_group_commit_records", cohort)

    def sync(self) -> None:
        with self._lock:
            target = self._written_ticket
            if self._fh is not None:
                self._fh.flush()
                with timer("wal_fsync"):
                    os.fsync(self._fh.fileno())
        # an explicit full sync covers every written record: release any
        # parked cohort members up to the sampled ticket
        with self._gc_cond:
            if target > self._synced_ticket:
                self._synced_ticket = target
                self._gc_cond.notify_all()

    def read_from(self, start_seq: int) -> Iterator[Tuple[int, int, bytes]]:
        """Yield (seq, schema_version, payload) for all records with
        seq >= start_seq.

        A torn/corrupt record in the FINAL segment is a crash mid-append:
        the scan terminates cleanly AND the segment is truncated at the
        last good record (with a WARN) so later appends never land past
        the garbage — without the truncate, append-mode writes would bury
        the torn bytes mid-segment and brick the next replay. The same in
        an EARLIER segment means acknowledged writes were lost (bit rot) —
        replay aborts with StorageError rather than silently skipping to
        newer segments. Each record carries a CRC32 over its payload, so a
        corrupt-but-complete record is detected, never silently replayed."""
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
            segs = self._segments()
        for i, (first, path) in enumerate(segs):
            # skip whole segments below start_seq (next segment's first seq
            # bounds this one's contents)
            if i + 1 < len(segs) and segs[i + 1][0] <= start_seq:
                continue
            records, clean, good_pos = self._read_segment(path, start_seq)
            yield from records
            if not clean:
                if i + 1 < len(segs):
                    raise StorageError(
                        f"corrupt WAL record mid-log in {path}; refusing to "
                        f"replay past the gap")
                self._repair_torn_tail(path, good_pos)
                return  # torn tail of the active segment: normal crash

    def _repair_torn_tail(self, path: str, good_pos: int) -> None:
        """Drop a torn/corrupt tail record left by a crash mid-append."""
        with self._lock:
            if self._fh is not None and self._fh_path == path:
                return  # segment reopened for appends already; leave it
            try:
                size = os.path.getsize(path)
                logger.warning(
                    "wal %s: torn/corrupt tail record; truncating %d bytes "
                    "at offset %d (crash mid-append)", path,
                    size - good_pos, good_pos)
                with open(path, "rb+") as f:
                    f.truncate(good_pos)
                    os.fsync(f.fileno())
            except OSError as e:  # pragma: no cover
                raise StorageError(f"wal tail repair failed: {e}", cause=e)

    def _read_segment(self, path: str, start_seq: int
                      ) -> Tuple[List[Tuple[int, int, bytes]], bool, int]:
        """Returns (records >= start_seq, clean, offset past the last good
        record) — the offset is the truncation point on a torn tail."""
        try:
            with open(path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return [], True, 0
        out: List[Tuple[int, int, bytes]] = []
        pos = 0
        n = len(data)
        while pos + _REC_HDR.size <= n:
            ln, crc, seq, sv = _REC_HDR.unpack_from(data, pos)
            body_start = pos + _REC_HDR.size
            if body_start + ln > n:
                return out, False, pos  # torn record
            payload = data[body_start:body_start + ln]
            if zlib.crc32(payload) != crc:
                return out, False, pos  # corrupt record
            pos = body_start + ln
            if seq >= start_seq:
                out.append((seq, sv, payload))
        return out, pos == n, pos

    def obsolete(self, seq: int) -> None:
        """Delete segments whose entire contents are <= seq."""
        with self._lock:
            segs = self._segments()
            # a segment can be deleted if the NEXT segment starts at <= seq+1,
            # meaning every record in it has seq <= that bound.
            for i, (first, path) in enumerate(segs):
                if i + 1 < len(segs) and segs[i + 1][0] <= seq + 1:
                    if self._fh_path == path and self._fh is not None:
                        continue  # never delete the active segment
                    try:
                        os.unlink(path)
                    except OSError as e:  # pragma: no cover
                        raise StorageError(f"wal gc failed: {e}", cause=e)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                os.fsync(self._fh.fileno())
                self._fh.close()
                self._fh = None


class NoopWal(Wal):
    """WAL-less mode for tests/benchmarks (reference: src/log-store/src/noop.rs)."""

    sync_on_write = False

    def __init__(self):  # noqa: super-init-not-called
        self._lock = TrackedLock("storage.wal")

    def group_commit_active(self):
        return False

    def append(self, seq, payload, schema_version=0):
        pass

    def append_async(self, seq, payload, schema_version=0):
        return 0

    def wait_durable(self, ticket):
        pass

    def sync(self):
        pass

    def read_from(self, start_seq):
        return iter(())

    def obsolete(self, seq):
        pass

    def close(self):
        pass
