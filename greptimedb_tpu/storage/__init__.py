from .engine import StorageEngine, EngineConfig  # noqa: F401
from .region import Region, RegionDescriptor  # noqa: F401
from .write_batch import WriteBatch, Mutation  # noqa: F401
