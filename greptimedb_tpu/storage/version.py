"""MVCC region version control.

Reference behavior: src/storage/src/version.rs — an immutable `Version`
snapshot (schema + memtables + SST levels + sequences) swapped atomically
under a lock; readers grab the current version without blocking writers.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, replace
from typing import List, Optional, Sequence

from ..common.locks import TrackedLock
from ..datatypes import Schema
from .memtable import Memtable, MemtableVersion
from .series import SeriesDict
from .sst import FileMeta, LevelMetas


@dataclass(frozen=True)
class Version:
    schema: Schema
    memtables: MemtableVersion
    ssts: LevelMetas
    flushed_sequence: int
    manifest_version: int


class VersionControl:
    def __init__(self, version: Version, committed_sequence: int = 0):
        self._lock = TrackedLock("storage.version", io_ok=False)
        self._current = version
        self._committed_sequence = committed_sequence

    @property
    def current(self) -> Version:
        return self._current

    @property
    def committed_sequence(self) -> int:
        return self._committed_sequence

    def set_committed_sequence(self, seq: int) -> None:
        self._committed_sequence = seq

    def next_sequence(self) -> int:
        return self._committed_sequence + 1

    # ---- transitions (called under the region writer lock) ----
    def freeze_mutable(self, new_mutable: Memtable) -> None:
        with self._lock:
            v = self._current
            self._current = replace(v, memtables=v.memtables.freeze(new_mutable))

    def apply_flush(self, *, memtable_ids: Sequence[int],
                    files: Sequence[FileMeta], flushed_sequence: int,
                    manifest_version: int) -> None:
        with self._lock:
            v = self._current
            self._current = replace(
                v,
                memtables=v.memtables.remove_immutables(memtable_ids),
                ssts=v.ssts.add_files(files),
                flushed_sequence=max(v.flushed_sequence, flushed_sequence),
                manifest_version=manifest_version)

    def apply_compaction(self, *, removed: Sequence[str],
                         added: Sequence[FileMeta],
                         manifest_version: int) -> None:
        with self._lock:
            v = self._current
            self._current = replace(
                v, ssts=v.ssts.remove_files(removed).add_files(added),
                manifest_version=manifest_version)

    def apply_schema_change(self, schema: Schema, new_mutable: Memtable,
                            manifest_version: int) -> None:
        with self._lock:
            v = self._current
            self._current = replace(
                v, schema=schema,
                memtables=v.memtables.freeze(new_mutable),
                manifest_version=manifest_version)
