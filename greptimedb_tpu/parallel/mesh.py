"""Device-mesh construction and row-sharding helpers.

A query's row stream is sharded over the full mesh (both axes flattened):
each device holds an equal, padded slice of the scan. Group-by results are
tiny (num_groups entries) and are kept replicated after an all-reduce.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

# Mesh axis names. "region" is the cross-host (DCN) axis regions shard over;
# "block" is the within-host (ICI) axis row blocks shard over.
REGION_AXIS = "region"
BLOCK_AXIS = "block"
ROW_AXES = (REGION_AXIS, BLOCK_AXIS)


def _split_factor(n: int) -> Tuple[int, int]:
    """Factor n into (region, block) with region <= block, preferring a
    near-square split so both collectives axes get exercised."""
    best = (1, n)
    for r in range(1, int(np.sqrt(n)) + 1):
        if n % r == 0:
            best = (r, n // r)
    return best


def make_mesh(devices: Optional[Sequence[jax.Device]] = None,
              region: Optional[int] = None,
              block: Optional[int] = None) -> Mesh:
    """Build a 2D ("region", "block") mesh over the given devices.

    With neither axis size given, factors the device count near-square.
    On a single device this yields a (1, 1) mesh: the same code path runs
    unsharded (shard_map with full specs) so tests and production share code.
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    n = len(devs)
    if region is None and block is None:
        region, block = _split_factor(n)
    elif region is None:
        region = n // block
    elif block is None:
        block = n // region
    if region * block != n:
        raise ValueError(f"mesh {region}x{block} != {n} devices")
    arr = np.asarray(devs).reshape(region, block)
    return Mesh(arr, ROW_AXES)


def pad_rows_to_multiple(n: int, multiple: int) -> int:
    """Rows per device must be equal across the mesh; round n up."""
    if multiple <= 1:
        return n
    return ((n + multiple - 1) // multiple) * multiple
