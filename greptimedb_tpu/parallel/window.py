"""Sequence/series-parallel window evaluation over the mesh.

The reference scales the time axis with pruned time-range SSTs and streaming
`RangeArray` windows (SURVEY.md §5 "long-context analog"). On a mesh the two
long-context strategies are:

- **series sharding** (Ulysses analog): each device owns a slice of the
  series axis and evaluates windows for its series entirely locally —
  PromQL's per-series independence means zero collectives until the final
  cross-series aggregation.
- **time blocking** (ring/blockwise analog): the time axis is split into
  contiguous blocks across devices; a window straddling a block boundary
  needs the tail of the previous block, which arrives as a halo via
  `ppermute` along the block axis — one neighbor hop, never a broadcast.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.window import (
    CUMSUM_OPS,
    GATHER_OPS,
    range_aggregate_cumsum,
    range_aggregate_gather,
    TS_PAD,
)
from .mesh import BLOCK_AXIS, REGION_AXIS, ROW_AXES


def _range_dispatch(ts2d, val2d, lengths, t0, step, range_ms, *, op, nsteps,
                    maxw, param, param2, series_block):
    if op in CUMSUM_OPS:
        return range_aggregate_cumsum(ts2d, val2d, lengths, t0, step,
                                      range_ms, op=op, nsteps=nsteps,
                                      param=param)
    if op in GATHER_OPS:
        return range_aggregate_gather(ts2d, val2d, t0, step, range_ms, op=op,
                                      nsteps=nsteps, maxw=maxw, param=param,
                                      param2=param2, series_block=series_block)
    raise ValueError(f"unknown range op: {op}")


@functools.partial(jax.jit, static_argnames=("op", "nsteps", "maxw", "mesh"))
def _series_sharded(ts2d, val2d, lengths, t0, step, range_ms, param, param2,
                    *, op, nsteps, maxw, mesh):
    # size the gather path's series blocking to the per-shard slice so small
    # shards don't pad up to the global default block of 128
    per_shard = max(1, ts2d.shape[0] // mesh.size)
    inner = functools.partial(_range_dispatch, op=op, nsteps=nsteps, maxw=maxw,
                              series_block=min(128, per_shard))
    fn = lambda t, v, l, a, b, c, p, p2: inner(t, v, l, a, b, c, param=p,
                                               param2=p2)
    srow = P(ROW_AXES, None)
    return jax.shard_map(
        fn, mesh=mesh,
        in_specs=(srow, srow, P(ROW_AXES), P(), P(), P(), P(), P()),
        out_specs=(srow, srow), check_vma=False)(
        ts2d, val2d, lengths, t0, step, range_ms, param, param2)


def series_sharded_range_aggregate(
    ts2d: np.ndarray, val2d: np.ndarray, lengths: np.ndarray,
    t0, step, range_ms, *, op: str, nsteps: int, mesh: Mesh,
    maxw: int = 128, param: float = 0.0, param2: float = 0.0,
) -> Tuple[jax.Array, jax.Array]:
    """Evaluate a range function with the series axis sharded over the mesh.

    Pads the series axis to a mesh multiple (padded series produce ok=False
    rows that are sliced off). Returns (result [S, nsteps], ok [S, nsteps]).
    """
    S = ts2d.shape[0]
    pad = (-S) % mesh.size if mesh.size > 1 else 0
    if S == 0:
        raise ValueError("series_sharded_range_aggregate: empty series axis")
    if isinstance(ts2d, np.ndarray) and ts2d.dtype == np.int64:
        # jnp silently narrows int64→int32 when x64 is off; rebase instead
        # of truncating (callers with epoch-ms timestamps should pass the
        # SeriesMatrix.device_arrays form — this is the safety net, shared
        # with the single-chip wrappers)
        from ..ops.window import _rebase_i64_host
        ts2d, t0 = _rebase_i64_host(ts2d, t0, step, nsteps, range_ms)
    if pad:
        # sentinel must fit the (possibly rebased-to-int32) ts dtype
        sentinel = np.iinfo(ts2d.dtype).max
        ts2d = np.pad(ts2d, ((0, pad), (0, 0)), constant_values=sentinel)
        val2d = np.pad(val2d, ((0, pad), (0, 0)))
        lengths = np.pad(lengths, (0, pad))
    shard2d = NamedSharding(mesh, P(ROW_AXES, None))
    shard1d = NamedSharding(mesh, P(ROW_AXES))
    out, ok = _series_sharded(
        jax.device_put(ts2d, shard2d), jax.device_put(val2d, shard2d),
        jax.device_put(lengths, shard1d),
        jnp.asarray(t0, ts2d.dtype), jnp.asarray(step, ts2d.dtype),
        jnp.asarray(range_ms, ts2d.dtype),
        jnp.asarray(param, val2d.dtype), jnp.asarray(param2, val2d.dtype),
        op=op, nsteps=nsteps, maxw=maxw, mesh=mesh)
    return out[:S], ok[:S]


def _blocked_window(vals, window: int, op: str):
    """Per-shard: trailing-window reduce over a dense step grid with a halo
    of (window-1) columns fetched from the left neighbor over ICI."""
    S, T = vals.shape
    halo = window - 1
    if halo > 0:
        nblocks = jax.lax.axis_size(BLOCK_AXIS)
        tail = vals[:, T - halo:]
        perm = [(i, i + 1) for i in range(nblocks - 1)]
        left = jax.lax.ppermute(tail, BLOCK_AXIS, perm)  # block 0 gets zeros
        if op in ("min", "max"):
            # zero is not the identity for min/max: block 0's halo (which
            # ppermute leaves zero-filled) must be ±inf instead
            ident0 = jnp.array(jnp.inf if op == "min" else -jnp.inf,
                               vals.dtype)
            is_first = jax.lax.axis_index(BLOCK_AXIS) == 0
            left = jnp.where(is_first, ident0, left)
        ext = jnp.concatenate([left, vals], axis=1)      # [S, halo + T]
    else:
        ext = vals
    if op == "sum" or op == "avg":
        acc_dtype = jnp.promote_types(vals.dtype, jnp.float32)
        cs = jnp.cumsum(ext.astype(acc_dtype), axis=1)
        csp = jnp.concatenate([jnp.zeros((S, 1), acc_dtype), cs], axis=1)
        out = csp[:, window:] - csp[:, :-window] if halo else csp[:, 1:] - csp[:, :-1]
        if op == "avg":
            out = out / window
        return out.astype(vals.dtype)
    if op in ("min", "max"):
        # log-step doubling (associative trailing reduce)
        acc = ext
        shift = 1
        red = jnp.minimum if op == "min" else jnp.maximum
        ident = jnp.array(jnp.inf if op == "min" else -jnp.inf, ext.dtype)
        while shift < window:
            take = min(shift, window - shift)
            rolled = jnp.concatenate(
                [jnp.full((S, take), ident), acc[:, :-take]], axis=1)
            acc = red(acc, rolled)
            shift += take
        return acc[:, halo:]
    raise ValueError(f"unsupported blocked window op: {op}")


@functools.partial(jax.jit, static_argnames=("window", "op", "mesh"))
def _time_blocked(vals, *, window, op, mesh):
    fn = functools.partial(_blocked_window, window=window, op=op)
    spec = P(REGION_AXIS, BLOCK_AXIS)
    return jax.shard_map(fn, mesh=mesh, in_specs=(spec,), out_specs=spec,
                         check_vma=False)(vals)


def time_blocked_window_sum(vals: np.ndarray, *, window: int, op: str = "sum",
                            mesh: Mesh) -> jax.Array:
    """Trailing-window reduce over a dense [series, steps] grid with the time
    axis sharded over the block axis (the downsampling inner loop).

    result[s, t] = op(vals[s, t-window+1 .. t]); leading steps treat
    out-of-range samples as 0 (sum/avg) or identity (min/max). The series
    axis shards over the region axis. Requires steps % block_axis == 0 and
    window-1 <= steps per block (one-hop halo).
    """
    region_n, block_n = (mesh.shape[REGION_AXIS], mesh.shape[BLOCK_AXIS])
    S, T = vals.shape
    pad_s = (-S) % region_n
    if pad_s:
        vals = np.pad(vals, ((0, pad_s), (0, 0)))
    if T % block_n:
        raise ValueError(f"steps {T} not divisible by block axis {block_n}")
    if window - 1 > T // block_n:
        raise ValueError(f"window {window} exceeds one block + halo "
                         f"({T // block_n} steps/block)")
    sharding = NamedSharding(mesh, P(REGION_AXIS, BLOCK_AXIS))
    out = _time_blocked(jax.device_put(vals, sharding), window=window, op=op,
                        mesh=mesh)
    return out[:S]
