"""Distributed group-by aggregation: psum of per-shard partial moments.

This is the TPU-native replacement for the reference's distributed scan
fan-out + frontend-side merge (src/frontend/src/table.rs:109-156,414-450) —
and an upgrade over it: v0.2 pushes only scans to datanodes and aggregates on
the frontend, while here every device reduces its own rows to per-group
moments and a single `psum`/`pmin`/`pmax` over the mesh finishes the job.

Decomposable moments per op (classic partial-aggregation algebra):
  sum, count           -> psum
  avg                  -> psum(sum), psum(count)
  stddev/variance      -> psum(sum), psum(sum_sq), psum(count)
  min/max              -> pmin/pmax with identity fill
  first/last           -> arg-extreme on (ts, global row index): pmin of the
                          encoded winner index, then a one-hot psum of its value
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.kernels import AGG_OPS, _max_ident, _min_ident, check_i64_safe
from .mesh import ROW_AXES, pad_rows_to_multiple

_BIG_IDX = np.iinfo(np.int32).max


def _partial_aggregate(gids, mask, ts, row_idx, values, col_masks, *,
                       num_groups, ops, has_col_masks, axes):
    """Runs per-shard; reduces over `axes` with XLA collectives.

    Returns (results, counts) replicated across the mesh.
    """
    seg = num_groups + 1  # one scratch group for masked-out rows
    safe_gids = jnp.where(mask, gids, num_groups)

    def agg_mask(i):
        if has_col_masks:
            return mask & col_masks[i]
        return mask

    cache: Dict[Tuple[str, int], jax.Array] = {}

    def g_count(i, m):
        k = ("count", i if has_col_masks else -1)
        if k not in cache:
            local = jax.ops.segment_sum(m.astype(jnp.int32), safe_gids,
                                        num_segments=seg)[:num_groups]
            cache[k] = jax.lax.psum(local, axes)
        return cache[k]

    def g_sum(col, i, m, square=False):
        k = ("sumsq" if square else "sum", i)
        if k not in cache:
            if square:
                # square in float: col*col wraps int columns past ~46k
                colf = col.astype(jnp.promote_types(col.dtype, jnp.float32))
                v, dt = colf * colf, colf.dtype
            else:
                v, dt = col, col.dtype
            local = jax.ops.segment_sum(jnp.where(m, v, 0).astype(dt),
                                        safe_gids, num_segments=seg)[:num_groups]
            cache[k] = jax.lax.psum(local, axes)
        return cache[k]

    counts = g_count(0, mask) if not has_col_masks else jax.lax.psum(
        jax.ops.segment_sum(mask.astype(jnp.int32), safe_gids,
                            num_segments=seg)[:num_groups], axes)

    results = []
    for i, op in enumerate(ops):
        col, m = values[i], agg_mask(i)
        if op == "count":
            results.append(g_count(i, m))
        elif op == "sum":
            results.append(g_sum(col, i, m))
        elif op == "avg":
            s, c = g_sum(col, i, m), g_count(i, m)
            results.append(jnp.where(c > 0, s / jnp.maximum(c, 1), jnp.nan))
        elif op in ("stddev", "variance"):
            # Shifted one-pass moments: center on the GLOBAL (psum'd) mean
            # so every shard shifts identically — avoids int wraparound
            # and f32 cancellation on large, tight value distributions.
            colf = col.astype(jnp.promote_types(col.dtype, jnp.float32))
            c = g_count(i, m)
            gc = jnp.maximum(jax.lax.psum(jnp.sum(jnp.where(m, 1.0, 0.0)),
                                          axes), 1.0)
            shift = jax.lax.psum(jnp.sum(jnp.where(m, colf, 0.0)), axes) / gc
            d = jnp.where(m, colf - shift, 0.0)
            s = jax.lax.psum(jax.ops.segment_sum(
                d, safe_gids, num_segments=seg)[:num_groups], axes)
            sq = jax.lax.psum(jax.ops.segment_sum(
                d * d, safe_gids, num_segments=seg)[:num_groups], axes)
            cc = jnp.maximum(c, 1)
            # sample variance (ddof=1), matching the finalize in tpu_exec
            var = jnp.maximum(sq - (s / cc) * s, 0.0) / jnp.maximum(c - 1, 1)
            var = jnp.where(c >= 2, var, jnp.nan)
            results.append(jnp.sqrt(var) if op == "stddev" else var)
        elif op == "min":
            local = jax.ops.segment_min(
                jnp.where(m, col, _max_ident(col.dtype)), safe_gids,
                num_segments=seg)[:num_groups]
            results.append(jax.lax.pmin(local, axes))
        elif op == "max":
            local = jax.ops.segment_max(
                jnp.where(m, col, _min_ident(col.dtype)), safe_gids,
                num_segments=seg)[:num_groups]
            results.append(jax.lax.pmax(local, axes))
        elif op in ("first", "last"):
            # Winner = min global row index among rows achieving the global
            # extreme timestamp for the group; exactly one shard contributes.
            if op == "first":
                ext_local = jax.ops.segment_min(
                    jnp.where(m, ts, _max_ident(ts.dtype)), safe_gids,
                    num_segments=seg)
                ext = jax.lax.pmin(ext_local, axes)
            else:
                ext_local = jax.ops.segment_max(
                    jnp.where(m, ts, _min_ident(ts.dtype)), safe_gids,
                    num_segments=seg)
                ext = jax.lax.pmax(ext_local, axes)
            hit = m & (ts == ext[safe_gids])
            win_local = jax.ops.segment_min(
                jnp.where(hit, row_idx, _BIG_IDX), safe_gids,
                num_segments=seg)[:num_groups]
            win = jax.lax.pmin(win_local, axes)
            # one-hot gather of the winning value via psum
            n_local = col.shape[0]
            local_pos = jax.ops.segment_min(
                jnp.where(hit, jnp.arange(n_local, dtype=jnp.int32), n_local),
                safe_gids, num_segments=seg)[:num_groups]
            have = (win_local == win) & (win < _BIG_IDX) & (local_pos < n_local)
            safe_pos = jnp.minimum(local_pos, n_local - 1)
            # exactly one shard contributes, so a native-dtype psum is an
            # exact gather (no float32 round-trip for int/f64 columns)
            contrib = jnp.where(have, col[safe_pos], jnp.zeros((), col.dtype))
            val = jax.lax.psum(contrib, axes)
            empty = jnp.nan if jnp.issubdtype(col.dtype, jnp.floating) else 0
            results.append(jnp.where(win < _BIG_IDX, val, empty))
        else:
            raise ValueError(f"unsupported agg op: {op}")
    return tuple(results), counts


@functools.partial(
    jax.jit,
    static_argnames=("num_groups", "ops", "has_col_masks", "mesh"))
def _dist_agg(gids, mask, ts, row_idx, values, col_masks, *, num_groups, ops,
              has_col_masks, mesh):
    nv = len(values)
    nm = len(col_masks)
    row = P(ROW_AXES)
    in_specs = (row, row, row, row, (row,) * nv, (row,) * nm)
    out_specs = ((P(),) * len(ops), P())
    fn = functools.partial(_partial_aggregate, num_groups=num_groups, ops=ops,
                           has_col_masks=has_col_masks, axes=ROW_AXES)
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)(
        gids, mask, ts, row_idx, values, col_masks)


def distributed_grouped_aggregate(
    gids: np.ndarray, mask: np.ndarray, ts: np.ndarray,
    values: Sequence[np.ndarray], col_masks: Sequence[np.ndarray] = (), *,
    num_groups: int, ops: Sequence[str], mesh: Mesh,
) -> Tuple[Tuple[jax.Array, ...], jax.Array]:
    """Mesh-sharded twin of ops.kernels.grouped_aggregate.

    Pads rows to a multiple of the mesh size (padding is masked out), shards
    them over both mesh axes, and reduces partial per-group moments with XLA
    collectives. Results/counts come back replicated.
    """
    check_i64_safe(ts, what="distributed_grouped_aggregate ts")
    check_i64_safe(*values, what="distributed_grouped_aggregate values")
    for op in ops:
        if op not in AGG_OPS:
            raise ValueError(f"unsupported agg op: {op}")
    n = int(gids.shape[0])
    total = pad_rows_to_multiple(max(n, mesh.size), mesh.size)

    def pad(a, fill=0):
        a = np.asarray(a)
        if a.shape[0] == total:
            return a
        out = np.full((total,) + a.shape[1:], fill, dtype=a.dtype)
        out[:n] = a
        return out

    gids_p = pad(gids.astype(np.int32))
    mask_p = pad(np.asarray(mask, dtype=bool), False)
    ts_p = pad(ts)
    row_idx = np.arange(total, dtype=np.int32)
    values_p = tuple(pad(v) for v in values)
    masks_p = tuple(pad(np.asarray(m, dtype=bool), False) for m in col_masks)

    shard = NamedSharding(mesh, P(ROW_AXES))
    put = lambda a: jax.device_put(a, shard)
    return _dist_agg(put(gids_p), put(mask_p), put(ts_p), put(row_idx),
                     tuple(put(v) for v in values_p),
                     tuple(put(m) for m in masks_p),
                     num_groups=num_groups, ops=tuple(ops),
                     has_col_masks=bool(masks_p), mesh=mesh)
