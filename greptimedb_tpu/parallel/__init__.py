"""Distributed execution over JAX device meshes.

The reference scales by placing table regions on datanodes and fanning scans
out over gRPC (SURVEY.md §2.7/§2.8; reference: src/partition, src/frontend/
src/table.rs:109-156). Here the same two axes exist as a
`jax.sharding.Mesh`:

- ``region`` axis — the DCN/host axis: table regions (horizontal partitions)
  live on different hosts; cross-region partial aggregates reduce over it.
- ``block`` axis — the ICI/chip axis: rows within a region are blocked over
  the chips of one host.

All collectives are XLA collectives (psum/pmin/pmax/ppermute/all_gather)
emitted by `shard_map` — there is no NCCL/MPI translation layer.
"""

from .mesh import (
    make_mesh,
    pad_rows_to_multiple,
    ROW_AXES,
)
from .aggregate import distributed_grouped_aggregate
from .window import (
    series_sharded_range_aggregate,
    time_blocked_window_sum,
)

__all__ = [
    "make_mesh",
    "pad_rows_to_multiple",
    "ROW_AXES",
    "distributed_grouped_aggregate",
    "series_sharded_range_aggregate",
    "time_blocked_window_sum",
]
