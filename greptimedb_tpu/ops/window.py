"""PromQL range-vector evaluation as vmapped window reductions.

Reference behavior: src/promql — `RangeManipulate` materializes per-step
window views (`RangeArray`, a DictionaryArray trick) and evaluates range
functions row-by-row per series (aggr_over_time.rs, extrapolate_rate.rs).

TPU design: series are laid out as a dense padded matrix [S, L] sorted by
time within each row. For an aligned step grid t_j = start + j*step, the
window (t_j - range, t_j] of every series is located with a vmapped
`searchsorted`, and:

- sum/count/avg/stddev/rate/increase/delta/changes/resets/last/first/idelta
  evaluate O(1) per window from per-series prefix sums (cumsum path);
- min/max/quantile/deriv/predict_linear gather bounded windows (MAXW static)
  and reduce with masking (gather path).

Counter resets are handled with a per-series cumulative correction array so
`increase` is a pure difference of adjusted prefix values — no per-window
scan. Extrapolation follows Prometheus `extrapolatedRate` semantics
(reference: src/promql/src/functions/extrapolate_rate.rs:53-200).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

TS_PAD = np.iinfo(np.int64).max

CUMSUM_OPS = {
    "sum_over_time", "count_over_time", "avg_over_time", "stddev_over_time",
    "stdvar_over_time", "last_over_time", "first_over_time", "present_over_time",
    "rate", "increase", "delta", "idelta", "irate_num", "changes", "resets",
}
GATHER_OPS = {"min_over_time", "max_over_time", "quantile_over_time",
              "deriv", "predict_linear", "mad_over_time", "holt_winters"}
RANGE_OPS = CUMSUM_OPS | GATHER_OPS


class SeriesMatrix:
    """Dense padded [num_series, max_len] layout of a set of time series."""

    __slots__ = ("ts", "values", "lengths", "num_series", "max_len")

    def __init__(self, ts: np.ndarray, values: np.ndarray, lengths: np.ndarray):
        self.ts = ts
        self.values = values
        self.lengths = lengths
        self.num_series, self.max_len = ts.shape

    @staticmethod
    def build(series_ids: np.ndarray, ts: np.ndarray, values: np.ndarray,
              num_series: int, max_len: Optional[int] = None) -> "SeriesMatrix":
        """Build from flat arrays sorted by (series_id, ts). Rows whose
        series_id is outside [0, num_series) are dropped."""
        sel = (series_ids >= 0) & (series_ids < num_series)
        series_ids, ts, values = series_ids[sel], ts[sel], values[sel]
        counts = np.bincount(series_ids, minlength=num_series)
        longest = int(counts.max(initial=0))
        if max_len is not None and max_len < longest:
            raise ValueError(
                f"max_len={max_len} smaller than longest series ({longest} rows)")
        L = int(max_len if max_len is not None else max(longest, 1))
        # bucket L to powers of two to bound compile cache misses
        L = 1 << (L - 1).bit_length() if L > 1 else 1
        ts2d = np.full((num_series, L), TS_PAD, dtype=np.int64)
        val2d = np.zeros((num_series, L), dtype=values.dtype)
        offsets = np.zeros(num_series + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        col = np.arange(len(series_ids)) - offsets[series_ids]
        ts2d[series_ids, col] = ts
        val2d[series_ids, col] = values
        return SeriesMatrix(ts2d, val2d, counts.astype(np.int32))

    def device_arrays(self, base: Optional[int] = None):
        """Return (ts, values, lengths, base) ready for device transfer.

        On TPU x64 is typically disabled, so int64 epoch timestamps would
        silently truncate. When the time span fits, timestamps are rebased to
        int32 offsets from `base` (padding becomes int32 max, preserving the
        sentinel ordering); callers must rebase query times by the same base.
        """
        valid = self.ts != TS_PAD
        if base is None:
            base = int(self.ts[valid].min()) if valid.any() else 0
        span_ok = True
        if valid.any():
            span_ok = (int(self.ts[valid].max()) - base) < 2**31 - 1 and \
                base <= int(self.ts[valid].min())
        if span_ok:
            rel = np.where(valid, self.ts - base, np.iinfo(np.int32).max)
            return rel.astype(np.int32), self.values, self.lengths, base
        return self.ts, self.values, self.lengths, 0


def _counts_leq_grid(ts2d: jax.Array, t0, step, nsteps: int) -> jax.Array:
    """#samples per row with ts <= t0 + k*step, for k in [0, nsteps) —
    i.e. side='right' searchsorted against a REGULAR query grid, computed
    without gathers: bucketize every sample (elementwise), then a fused
    [S-chunk, L, T] compare-reduce. Measured 6.6x faster than vmapped
    searchsorted at the 10k-series × 8192-pt × 1440-step PromQL shape on
    v5e (890ms vs 5.9s per bounds array) — binary search is random-gather
    bound on TPU; this is pure VPU compare-adds."""
    S, L = ts2d.shape
    # smallest k with t0 + k*step >= ts  (pad sentinel maps to nsteps,
    # excluded from every window; pre-window samples map to 0).
    # The dtype-max pad sentinel would overflow t0 - ts for negative t0,
    # so pads are routed through t0 and forced to nsteps afterwards.
    sentinel = jnp.iinfo(ts2d.dtype).max
    is_pad = ts2d == sentinel
    safe_ts = jnp.where(is_pad, t0, ts2d)
    b = jnp.clip(-jnp.floor_divide(t0 - safe_ts, step), 0, nsteps) \
        .astype(jnp.int32)
    b = jnp.where(is_pad, nsteps, b)
    cmp_dtype = jnp.int16 if nsteps + 1 < 2**15 else jnp.int32
    b = b.astype(cmp_dtype)   # halve compare width: 2x VPU lanes
    ks = jnp.arange(nsteps, dtype=cmp_dtype)
    chunk = max(1, min(S, 512))
    pad = (-S) % chunk
    if pad:
        # padded rows are garbage and sliced off; padding avoids the
        # dynamic_slice start clamp silently duplicating rows
        b = jnp.concatenate(
            [b, jnp.full((pad, L), nsteps, b.dtype)], axis=0)
    outs = []
    for i in range(0, S + pad, chunk):
        part = jax.lax.dynamic_slice_in_dim(b, i, chunk, 0)
        outs.append((part[:, :, None] <= ks[None, None, :])
                    .sum(axis=1, dtype=jnp.int32))
    out = jnp.concatenate(outs, axis=0)
    return out[:S] if pad else out


#: above this row length the O(S*L*T) compare-reduce loses to the
#: O(S*T*log L) gather-bound binary search (crossover ~55k at measured
#: v5e gather/VPU rates)
_BUCKETIZE_MAX_LEN = 32768


@functools.partial(jax.jit, static_argnames=("step", "range_ms", "nsteps"))
def compute_window_bounds(ts2d, t0, *, step: int, range_ms: int,
                          nsteps: int) -> Tuple[jax.Array, jax.Array]:
    """Standalone window-bounds kernel for callers that reuse bounds across
    range functions (rate + avg_over_time over one selector share them —
    the bounds pass dominates PromQL evaluation at 10k-series scale).

    When the window is step-aligned (range % step == 0, the common PromQL
    shape) and the extension is not wider than the grid itself, lo is a
    shifted hi: ONE extended compare-reduce over T + range/step steps
    replaces the two separate passes. Wide-range instant queries
    (shift >> nsteps, e.g. rate(x[1d]) at one step) keep the two-pass
    form, which is O(nsteps)."""
    T = int(nsteps)
    L = ts2d.shape[1]
    if (L <= _BUCKETIZE_MAX_LEN and T > 1 and step > 0
            and range_ms % step == 0 and range_ms >= 0
            and range_ms // step <= T):
        shift = range_ms // step
        ext = _ext_counts(ts2d, t0, step=step, range_ms=range_ms, nsteps=T)
        return ext[:, :T], ext[:, shift:]
    step_ends = t0 + jnp.arange(T, dtype=ts2d.dtype) * step
    return window_bounds(ts2d, step_ends, range_ms)


def window_bounds(ts2d: jax.Array, step_ends: jax.Array, range_ms: int
                  ) -> Tuple[jax.Array, jax.Array]:
    """lo/hi [S, T]: window (end - range, end] as index ranges [lo, hi)."""
    T = int(step_ends.shape[0])
    if ts2d.shape[1] <= _BUCKETIZE_MAX_LEN and T > 1:
        # step_ends is a regular grid by construction (t0 + k*step)
        t0 = step_ends[0]
        step = step_ends[1] - step_ends[0]
        hi = _counts_leq_grid(ts2d, t0, step, T)
        lo = _counts_leq_grid(ts2d, t0 - range_ms, step, T)
        return lo, hi
    ss = jax.vmap(lambda row, v: jnp.searchsorted(row, v, side="right"),
                  in_axes=(0, None))
    lo = ss(ts2d, step_ends - range_ms)
    hi = ss(ts2d, step_ends)
    return lo.astype(jnp.int32), hi.astype(jnp.int32)


def _gather(row2d: jax.Array, idx: jax.Array) -> jax.Array:
    """Gather row2d[s, idx[s, t]] → [S, T] (idx clipped by caller)."""
    return jnp.take_along_axis(row2d, idx, axis=1)


def _rebase_i64_host(ts2d, t0, step=0, nsteps=1, range_ms=0):
    """Host-validating guard against silent int64→int32 narrowing.

    With jax_enable_x64 off (the norm on TPU), `jnp.asarray` narrows int64
    host arrays to int32: epoch-ms timestamps wrap negative and the TS_PAD
    sentinel becomes -1, breaking the sorted-order precondition every range
    kernel relies on. When handed a host int64 ts matrix in that regime,
    rebase it to int32 offsets from its minimum (remapping TS_PAD to int32
    max so padding still sorts last) and shift t0 by the same base. Device
    arrays and non-int64 inputs pass through untouched.

    The whole quantity range the kernel computes with must fit int32:
    the data span, t0, the last step end t0 + (nsteps-1)*step, and the
    earliest window start t0 - range_ms are all validated (strictly below
    int32 max: a sample rebasing exactly to int32 max would alias the pad
    sentinel and be silently dropped).

    Returns (ts2d, t0) safe to hand to jit.
    """
    if jax.config.jax_enable_x64:
        return ts2d, t0
    if not (isinstance(ts2d, np.ndarray) and ts2d.dtype == np.int64):
        return ts2d, t0
    valid = ts2d != TS_PAD
    if valid.any():
        base, hi = int(ts2d[valid].min()), int(ts2d[valid].max())
    else:
        # no samples: rebase the query grid onto itself so evaluation
        # proceeds and every step reports ok=False (not a crash)
        base = hi = int(t0)
    i32 = np.iinfo(np.int32)
    last_end = int(t0) + (int(nsteps) - 1) * int(step)
    bounds = [hi - base, int(t0) - base, last_end - base,
              int(t0) - int(range_ms) - base]
    if any(b >= i32.max or b < i32.min for b in bounds):
        raise ValueError(
            f"timestamp/query span after rebase exceeds int32 "
            f"({min(bounds)}..{max(bounds)}) and x64 is disabled: rebase to "
            f"region-relative offsets first (see SeriesMatrix.device_arrays)")
    rel = np.where(valid, ts2d - base, i32.max).astype(np.int32)
    return rel, np.int32(int(t0) - base)


def range_aggregate_cumsum(
    ts2d, val2d, lengths, t0, step, range_ms, *, op: str, nsteps: int,
    param: float = 0.0, bounds: Optional[Tuple[jax.Array, jax.Array]] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Evaluate a cumsum-path range function on the aligned step grid.

    Returns (result [S, T], ok [S, T]) — ok False means "no point for this
    series at this step" (NaN / absent in PromQL terms).

    Host int64 timestamps are auto-rebased when x64 is off (step/range are
    deltas and stay as passed; t0 shifts with the base). `bounds` lets
    callers reuse one `compute_window_bounds` result across several range
    functions over the same selector — the bounds pass dominates PromQL
    evaluation at 10k-series scale.
    """
    ts2d, t0 = _rebase_i64_host(ts2d, t0, step, nsteps, range_ms)
    if bounds is not None:
        return _range_aggregate_cumsum_pre(
            ts2d, val2d, lengths, t0, step, range_ms, bounds[0], bounds[1],
            op=op, nsteps=nsteps, param=param)
    return _range_aggregate_cumsum(ts2d, val2d, lengths, t0, step, range_ms,
                                   op=op, nsteps=nsteps, param=param)


@functools.partial(jax.jit, static_argnames=("op", "nsteps"))
def _range_aggregate_cumsum(
    ts2d: jax.Array, val2d: jax.Array, lengths: jax.Array,
    t0, step, range_ms, *, op: str, nsteps: int, param: float = 0.0,
) -> Tuple[jax.Array, jax.Array]:
    step_ends = t0 + jnp.arange(nsteps, dtype=ts2d.dtype) * step
    lo, hi = window_bounds(ts2d, step_ends, range_ms)
    return _rac_body(ts2d, val2d, lengths, lo, hi, step_ends, range_ms,
                     op=op, nsteps=nsteps)


@functools.partial(jax.jit, static_argnames=("op", "nsteps"))
def _range_aggregate_cumsum_pre(
    ts2d: jax.Array, val2d: jax.Array, lengths: jax.Array,
    t0, step, range_ms, lo, hi, *, op: str, nsteps: int, param: float = 0.0,
) -> Tuple[jax.Array, jax.Array]:
    step_ends = t0 + jnp.arange(nsteps, dtype=ts2d.dtype) * step
    return _rac_body(ts2d, val2d, lengths, lo, hi, step_ends, range_ms,
                     op=op, nsteps=nsteps)


def _rac_body(ts2d, val2d, lengths, lo, hi, step_ends, range_ms, *,
              op: str, nsteps: int) -> Tuple[jax.Array, jax.Array]:
    S, L = ts2d.shape
    idx = jnp.arange(L, dtype=jnp.int32)
    valid = idx[None, :] < lengths[:, None]
    fv = val2d.dtype
    count = (hi - lo).astype(jnp.int32)
    ok1 = count >= 1
    hi1 = jnp.maximum(hi - 1, 0)

    def pick_first():
        return _gather(val2d, jnp.minimum(lo, L - 1))

    def pick_last():
        return _gather(val2d, hi1)

    if op in ("count_over_time", "present_over_time"):
        if op == "present_over_time":
            return jnp.ones_like(count, dtype=fv), ok1
        return count.astype(fv), ok1

    if op in ("sum_over_time", "avg_over_time", "stddev_over_time",
              "stdvar_over_time"):
        vz = jnp.where(valid, val2d, 0).astype(fv)
        cs = jnp.cumsum(vz, axis=1)
        csp = jnp.concatenate([jnp.zeros((S, 1), fv), cs], axis=1)
        wsum = _gather(csp, hi) - _gather(csp, lo)
        if op == "sum_over_time":
            return wsum, ok1
        cnt = jnp.maximum(count, 1).astype(fv)
        mean = wsum / cnt
        if op == "avg_over_time":
            return mean, ok1
        cs2 = jnp.cumsum(vz * vz, axis=1)
        cs2p = jnp.concatenate([jnp.zeros((S, 1), fv), cs2], axis=1)
        wsq = _gather(cs2p, hi) - _gather(cs2p, lo)
        var = jnp.maximum(wsq / cnt - mean * mean, 0.0)
        if op == "stdvar_over_time":
            return var, ok1
        return jnp.sqrt(var), ok1

    if op == "first_over_time":
        return pick_first(), ok1
    if op == "last_over_time":
        return pick_last(), ok1

    if op in ("idelta", "irate_num"):
        ok2 = count >= 2
        last = pick_last()
        prev = _gather(val2d, jnp.maximum(hi - 2, 0))
        if op == "irate_num":
            # prometheus instantValue counter-reset rule: on reset
            # (last < prev) the delta is the last sample alone
            return jnp.where(last < prev, last, last - prev), ok2
        return last - prev, ok2

    if op in ("changes", "resets"):
        prev = jnp.concatenate([val2d[:, :1], val2d[:, :-1]], axis=1)
        pair_ok = valid & (idx[None, :] >= 1)
        if op == "changes":
            ind = pair_ok & (val2d != prev)
        else:
            ind = pair_ok & (val2d < prev)
        ci = jnp.cumsum(ind.astype(jnp.int32), axis=1)
        cip = jnp.concatenate([jnp.zeros((S, 1), jnp.int32), ci], axis=1)
        # pairs (i-1, i) with both endpoints inside [lo, hi)
        cnt = _gather(cip, hi) - _gather(cip, jnp.minimum(lo + 1, L))
        cnt = jnp.where(count >= 1, cnt, 0)
        return cnt.astype(fv), ok1

    if op in ("rate", "increase", "delta"):
        ok2 = count >= 2
        first_t = _gather(ts2d, jnp.minimum(lo, L - 1)).astype(fv)
        last_t = _gather(ts2d, hi1).astype(fv)
        first_v = pick_first()
        last_v = pick_last()
        if op == "delta":
            raw = last_v - first_v
            is_counter = False
        else:
            # counter-reset correction: adjusted[i] = v[i] + sum of resets<=i
            prev = jnp.concatenate([val2d[:, :1], val2d[:, :-1]], axis=1)
            pair_ok = valid & (idx[None, :] >= 1)
            contrib = jnp.where(pair_ok & (val2d < prev), prev, 0).astype(fv)
            corr = jnp.cumsum(contrib, axis=1)
            adj = val2d + corr
            raw = _gather(adj, hi1) - _gather(adj, jnp.minimum(lo, L - 1))
            is_counter = True
        return _extrapolate(raw, first_t, last_t, first_v, count, step_ends,
                            range_ms, op=op, is_counter=is_counter)

    raise ValueError(f"not a cumsum-path op: {op}")


def _extrapolate(raw, first_t, last_t, first_v, count, step_ends, range_ms,
                 *, op: str, is_counter: bool):
    """Prometheus extrapolation epilogue (extrapolate_rate.rs:100-200),
    shared by the per-op kernel and the stacked-gather fast path."""
    fv = raw.dtype
    ok2 = count >= 2
    ms = jnp.asarray(range_ms, fv)
    range_start = step_ends[None, :].astype(fv) - ms
    range_end = step_ends[None, :].astype(fv)
    dur_to_start = first_t - range_start
    dur_to_end = range_end - last_t
    sampled = last_t - first_t
    avg_dur = sampled / jnp.maximum(count - 1, 1).astype(fv)
    threshold = avg_dur * 1.1
    if is_counter:
        # cap extrapolation below zero for counters (only meaningful when
        # the first sample is non-negative, per extrapolate_rate.rs)
        dur_to_zero = jnp.where((raw > 0) & (first_v >= 0),
                                sampled * (first_v / jnp.where(raw == 0, 1, raw)),
                                jnp.inf)
        dur_to_start = jnp.minimum(dur_to_start, dur_to_zero)
    ext_start = jnp.where(dur_to_start < threshold, dur_to_start, avg_dur / 2)
    ext_end = jnp.where(dur_to_end < threshold, dur_to_end, avg_dur / 2)
    factor = (sampled + ext_start + ext_end) / jnp.where(sampled == 0, 1, sampled)
    out = raw * factor
    if op == "rate":
        out = out / (ms / 1000.0)
    return out, ok2 & (sampled > 0)


def range_aggregate_gather(
    ts2d, val2d, t0, step, range_ms, *, op: str, nsteps: int, maxw: int,
    param: float = 0.0, param2: float = 0.0, series_block: int = 128,
    bounds: Optional[Tuple[jax.Array, jax.Array]] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Gather-path range functions (host int64 ts auto-rebased, see
    `range_aggregate_cumsum`; `bounds` reuses a `compute_window_bounds`
    result)."""
    ts2d, t0 = _rebase_i64_host(ts2d, t0, step, nsteps, range_ms)
    if bounds is not None:
        return _range_aggregate_gather_pre(
            ts2d, val2d, t0, step, range_ms, bounds[0], bounds[1], op=op,
            nsteps=nsteps, maxw=maxw, param=param, param2=param2,
            series_block=series_block)
    return _range_aggregate_gather(ts2d, val2d, t0, step, range_ms, op=op,
                                   nsteps=nsteps, maxw=maxw, param=param,
                                   param2=param2, series_block=series_block)


@functools.partial(jax.jit, static_argnames=("op", "nsteps", "maxw", "series_block"))
def _range_aggregate_gather(
    ts2d: jax.Array, val2d: jax.Array,
    t0, step, range_ms, *, op: str, nsteps: int, maxw: int,
    param: float = 0.0, param2: float = 0.0, series_block: int = 128,
) -> Tuple[jax.Array, jax.Array]:
    return _rag_body(ts2d, val2d, t0, step, range_ms, None, None, op=op,
                     nsteps=nsteps, maxw=maxw, param=param, param2=param2,
                     series_block=series_block)


@functools.partial(jax.jit, static_argnames=("op", "nsteps", "maxw", "series_block"))
def _range_aggregate_gather_pre(
    ts2d: jax.Array, val2d: jax.Array,
    t0, step, range_ms, lo, hi, *, op: str, nsteps: int, maxw: int,
    param: float = 0.0, param2: float = 0.0, series_block: int = 128,
) -> Tuple[jax.Array, jax.Array]:
    return _rag_body(ts2d, val2d, t0, step, range_ms, lo, hi, op=op,
                     nsteps=nsteps, maxw=maxw, param=param, param2=param2,
                     series_block=series_block)


def _rag_body(
    ts2d: jax.Array, val2d: jax.Array,
    t0, step, range_ms, pre_lo, pre_hi, *, op: str, nsteps: int, maxw: int,
    param: float = 0.0, param2: float = 0.0, series_block: int = 128,
) -> Tuple[jax.Array, jax.Array]:
    """Gather-path range functions: each window materializes ≤ maxw samples.

    Row validity comes from the TS_PAD sentinel (padded slots sort last and
    fall outside every window), so no lengths array is needed. Windows longer
    than maxw are truncated to their most recent maxw samples (callers size
    maxw from data density). Processed in series blocks via lax.map to bound
    VMEM footprint."""
    S, L = ts2d.shape
    step_ends = t0 + jnp.arange(nsteps, dtype=ts2d.dtype) * step
    pad_s = (-S) % series_block
    pad_sentinel = jnp.iinfo(ts2d.dtype).max
    ts2d = jnp.pad(ts2d, ((0, pad_s), (0, 0)), constant_values=pad_sentinel)
    val2d = jnp.pad(val2d, ((0, pad_s), (0, 0)))
    SB = (S + pad_s) // series_block
    have_bounds = pre_lo is not None
    if have_bounds:
        # padded series get empty windows (lo == hi == 0)
        pre_lo = jnp.pad(pre_lo, ((0, pad_s), (0, 0)))
        pre_hi = jnp.pad(pre_hi, ((0, pad_s), (0, 0)))

    def block(args):
        if have_bounds:
            tsb, valb, lo, hi = args  # [B, L] / [B, T]
        else:
            tsb, valb = args          # [B, L]
            lo, hi = window_bounds(tsb, step_ends, range_ms)
        lo = jnp.maximum(lo, hi - maxw)
        w = jnp.arange(maxw, dtype=jnp.int32)
        widx = lo[:, :, None] + w[None, None, :]            # [B, T, W]
        inwin = widx < hi[:, :, None]
        widx_c = jnp.minimum(widx, L - 1)
        vals = jnp.take_along_axis(jnp.broadcast_to(valb[:, None, :],
                                                    (valb.shape[0], nsteps, L)),
                                   widx_c, axis=2)
        tvals = jnp.take_along_axis(jnp.broadcast_to(tsb[:, None, :],
                                                     (tsb.shape[0], nsteps, L)),
                                    widx_c, axis=2)
        count = (hi - lo).astype(jnp.int32)
        ok1 = count >= 1
        fv = valb.dtype
        if op == "min_over_time":
            r = jnp.min(jnp.where(inwin, vals, jnp.inf), axis=2)
            return r, ok1
        if op == "max_over_time":
            r = jnp.max(jnp.where(inwin, vals, -jnp.inf), axis=2)
            return r, ok1
        if op == "mad_over_time":
            med = _masked_quantile(vals, inwin, 0.5)
            dev = jnp.abs(vals - med[:, :, None])
            r = _masked_quantile(dev, inwin, 0.5)
            return r, ok1
        if op == "quantile_over_time":
            return _masked_quantile(vals, inwin, param), ok1
        if op in ("deriv", "predict_linear"):
            ok2 = count >= 2
            # least-squares slope with times centered on the window end
            t_sec = (tvals.astype(fv) - step_ends[None, :, None].astype(fv)) / 1000.0
            m = inwin.astype(fv)
            n = jnp.maximum(jnp.sum(m, axis=2), 1)
            sx = jnp.sum(t_sec * m, axis=2)
            sy = jnp.sum(vals * m, axis=2)
            sxx = jnp.sum(t_sec * t_sec * m, axis=2)
            sxy = jnp.sum(t_sec * vals * m, axis=2)
            denom = n * sxx - sx * sx
            slope = jnp.where(denom != 0, (n * sxy - sx * sy) /
                              jnp.where(denom == 0, 1, denom), jnp.nan)
            if op == "deriv":
                return slope, ok2
            intercept = (sy - slope * sx) / n
            return intercept + slope * param, ok2
        if op == "holt_winters":
            return _holt_winters(vals, inwin, param, param2), count >= 2
        raise ValueError(f"not a gather-path op: {op}")

    operands = (ts2d.reshape(SB, series_block, L),
                val2d.reshape(SB, series_block, L))
    if have_bounds:
        operands += (pre_lo.reshape(SB, series_block, nsteps),
                     pre_hi.reshape(SB, series_block, nsteps))
    outs, oks = jax.lax.map(block, operands)
    out = outs.reshape(-1, nsteps)[:S]
    ok = oks.reshape(-1, nsteps)[:S]
    return out, ok


# ---------------------------------------------------------------------------
# Aligned-window shared evaluation (the PromQL dashboard fast path)
# ---------------------------------------------------------------------------
# When the window is a multiple of the step (rate(x[5m]) at 1m step — the
# common dashboard shape), every per-(series, step) quantity the cumsum-op
# family needs is a value at either index lo[k] or hi[k]-1, and lo is a
# shifted view of hi over an EXTENDED grid. Measured on v5e: a stacked
# [S, L, 8] take_along_axis costs the same as a single-channel gather
# (~275ms at 10k series x 1440 steps), so ONE stacked gather at the
# extended grid serves every op — rate + avg_over_time + ... over the same
# selector share the bounds pass, the cumsums, and the gather, leaving only
# tiny [S, T] vector epilogues per op.

# tier-A channels (prefix/instant values)
_CH_CSP, _CH_TS_PREV, _CH_TS_AT, _CH_VAL_PREV, _CH_VAL_AT, _CH_VAL_PREV2 = \
    range(6)


@jax.jit
def _stack_prefix(ts2d, val2d, lengths, ext):
    """Tier A: gather [csp, ts_prev, ts_at, val_prev, val_at, val_prev2]
    at the extended-grid positions; X_at[e] = X[min(e, L-1)],
    X_prev[e] = X[max(e-1, 0)], X_prev2[e] = X[max(e-2, 0)]."""
    S, L = ts2d.shape
    fv = val2d.dtype
    idx = jnp.arange(L, dtype=jnp.int32)
    valid = idx[None, :] < lengths[:, None]
    vz = jnp.where(valid, val2d, 0).astype(fv)
    csp = jnp.concatenate([jnp.zeros((S, 1), fv), jnp.cumsum(vz, axis=1)],
                          axis=1)
    tsf = ts2d.astype(fv)
    stack = jnp.stack([
        csp,
        jnp.concatenate([tsf[:, :1], tsf], axis=1),
        jnp.concatenate([tsf, tsf[:, -1:]], axis=1),
        jnp.concatenate([val2d[:, :1], val2d], axis=1).astype(fv),
        jnp.concatenate([val2d, val2d[:, -1:]], axis=1).astype(fv),
        jnp.concatenate([val2d[:, :1], val2d[:, :1], val2d[:, :-1]],
                        axis=1).astype(fv),
    ], axis=-1)
    e = jnp.minimum(ext, L)
    return jnp.take_along_axis(stack, e[:, :, None], axis=1)


@jax.jit
def _stack_counter(ts2d, val2d, lengths, ext):
    """Tier B: counter-reset-adjusted values [adj_prev, adj_at]."""
    S, L = ts2d.shape
    fv = val2d.dtype
    idx = jnp.arange(L, dtype=jnp.int32)
    valid = idx[None, :] < lengths[:, None]
    prev = jnp.concatenate([val2d[:, :1], val2d[:, :-1]], axis=1)
    pair_ok = valid & (idx[None, :] >= 1)
    contrib = jnp.where(pair_ok & (val2d < prev), prev, 0).astype(fv)
    adj = val2d + jnp.cumsum(contrib, axis=1)
    stack = jnp.stack([
        jnp.concatenate([adj[:, :1], adj], axis=1),
        jnp.concatenate([adj, adj[:, -1:]], axis=1),
    ], axis=-1)
    e = jnp.minimum(ext, L)
    return jnp.take_along_axis(stack, e[:, :, None], axis=1)


@jax.jit
def _stack_sq(ts2d, val2d, lengths, ext):
    """Tier C: squared-value prefix (stddev/stdvar only)."""
    S, L = ts2d.shape
    fv = val2d.dtype
    idx = jnp.arange(L, dtype=jnp.int32)
    valid = idx[None, :] < lengths[:, None]
    vz = jnp.where(valid, val2d, 0).astype(fv)
    csp2 = jnp.concatenate(
        [jnp.zeros((S, 1), fv), jnp.cumsum(vz * vz, axis=1)], axis=1)
    e = jnp.minimum(ext, L)
    return jnp.take_along_axis(csp2[:, :, None], e[:, :, None], axis=1)


@functools.partial(jax.jit, static_argnames=("step", "range_ms", "nsteps"))
def _ext_counts(ts2d, t0, *, step: int, range_ms: int, nsteps: int):
    """Counts at the extended grid [t0 - range, ..., t0 + (nsteps-1)*step]:
    lo = ext[:, :nsteps], hi = ext[:, shift:] for shift = range // step."""
    shift = range_ms // step
    T_ext = nsteps + shift
    if ts2d.shape[1] <= _BUCKETIZE_MAX_LEN and T_ext > 1:
        return _counts_leq_grid(ts2d, t0 - range_ms, step, T_ext)
    ends = (t0 - range_ms) + jnp.arange(T_ext, dtype=ts2d.dtype) * step
    ss = jax.vmap(lambda row, v: jnp.searchsorted(row, v, side="right"),
                  in_axes=(0, None))
    return ss(ts2d, ends).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("op", "nsteps", "shift"))
def _op_from_stack(ga, gb, gc, lo, hi, t0, step, range_ms, *,
                   op: str, nsteps: int, shift: int):
    T = nsteps
    fv = ga.dtype
    count = (hi - lo).astype(jnp.int32)
    ok1 = count >= 1

    def lo_of(x):
        return x[:, :T]

    def hi_of(x):
        return x[:, shift:]

    def A(c):
        return ga[..., c]

    if op == "sum_over_time":
        return hi_of(A(_CH_CSP)) - lo_of(A(_CH_CSP)), ok1
    if op in ("avg_over_time", "stddev_over_time", "stdvar_over_time"):
        wsum = hi_of(A(_CH_CSP)) - lo_of(A(_CH_CSP))
        cnt = jnp.maximum(count, 1).astype(fv)
        mean = wsum / cnt
        if op == "avg_over_time":
            return mean, ok1
        csp2 = gc[..., 0]
        wsq = hi_of(csp2) - lo_of(csp2)
        var = jnp.maximum(wsq / cnt - mean * mean, 0.0)
        return (var if op == "stdvar_over_time" else jnp.sqrt(var)), ok1
    if op == "first_over_time":
        return lo_of(A(_CH_VAL_AT)), ok1
    if op == "last_over_time":
        return hi_of(A(_CH_VAL_PREV)), ok1
    if op in ("idelta", "irate_num"):
        ok2 = count >= 2
        last = hi_of(A(_CH_VAL_PREV))
        prev = hi_of(A(_CH_VAL_PREV2))
        if op == "irate_num":
            return jnp.where(last < prev, last, last - prev), ok2
        return last - prev, ok2
    if op in ("rate", "increase", "delta"):
        step_ends = t0 + jnp.arange(T, dtype=jnp.int32) * step
        first_t = lo_of(A(_CH_TS_AT))
        last_t = hi_of(A(_CH_TS_PREV))
        first_v = lo_of(A(_CH_VAL_AT))
        last_v = hi_of(A(_CH_VAL_PREV))
        if op == "delta":
            raw = last_v - first_v
            is_counter = False
        else:
            raw = hi_of(gb[..., 0]) - lo_of(gb[..., 1])
            is_counter = True
        return _extrapolate(raw, first_t, last_t, first_v, count, step_ends,
                            range_ms, op=op, is_counter=is_counter)
    raise ValueError(f"not a stack-path op: {op}")


@functools.partial(jax.jit, static_argnames=("op", "fv"))
def _count_from_bounds(lo, hi, *, op: str, fv):
    # fv = value dtype, so results match the non-aligned kernel's dtype
    # (float64 under x64) regardless of which path a query takes
    count = (hi - lo).astype(jnp.int32)
    ok1 = count >= 1
    if op == "present_over_time":
        return jnp.ones_like(count, dtype=fv), ok1
    return count.astype(fv), ok1


class AlignedWindowEval:
    """Shared-state evaluator for cumsum-path range functions over one
    series matrix and one step-aligned grid (range % step == 0).

    Bounds, cumsums, and the stacked gather are computed once and cached;
    each op adds only a [S, T] vector epilogue. The PromQL engine caches
    one of these per (selector, window) within an evaluation."""

    def __init__(self, ts2d, val2d, lengths, t0, step, range_ms, nsteps):
        step, range_ms, nsteps = int(step), int(range_ms), int(nsteps)
        if step <= 0 or range_ms < 0 or range_ms % step:
            raise ValueError("AlignedWindowEval needs range % step == 0")
        ts2d, t0 = _rebase_i64_host(ts2d, t0, step, nsteps, range_ms)
        self.ts2d, self.val2d, self.lengths = ts2d, val2d, lengths
        self.t0, self.step, self.range_ms = t0, step, range_ms
        self.nsteps = nsteps
        self.shift = range_ms // step
        self._ext = None
        self._ga = self._gb = self._gc = None

    def ext(self):
        if self._ext is None:
            self._ext = _ext_counts(self.ts2d, self.t0, step=self.step,
                                    range_ms=self.range_ms,
                                    nsteps=self.nsteps)
        return self._ext

    def bounds(self) -> Tuple[jax.Array, jax.Array]:
        ext = self.ext()
        return ext[:, :self.nsteps], ext[:, self.shift:]

    def eval(self, op: str) -> Tuple[jax.Array, jax.Array]:
        if op not in CUMSUM_OPS:
            raise ValueError(f"not a cumsum-path op: {op}")
        lo, hi = self.bounds()
        if op in ("count_over_time", "present_over_time"):
            return _count_from_bounds(lo, hi, op=op,
                                      fv=self.val2d.dtype)
        if op in ("changes", "resets"):
            # outside the stack family; still shares the bounds pass
            return range_aggregate_cumsum(
                self.ts2d, self.val2d, self.lengths, self.t0, self.step,
                self.range_ms, op=op, nsteps=self.nsteps, bounds=(lo, hi))
        if self._ga is None:
            self._ga = _stack_prefix(self.ts2d, self.val2d, self.lengths,
                                     self.ext())
        gb = gc = None
        if op in ("rate", "increase"):
            if self._gb is None:
                self._gb = _stack_counter(self.ts2d, self.val2d,
                                          self.lengths, self.ext())
            gb = self._gb
        if op in ("stddev_over_time", "stdvar_over_time"):
            if self._gc is None:
                self._gc = _stack_sq(self.ts2d, self.val2d, self.lengths,
                                     self.ext())
            gc = self._gc
        return _op_from_stack(ga=self._ga, gb=gb, gc=gc, lo=lo, hi=hi,
                              t0=self.t0, step=self.step,
                              range_ms=self.range_ms, op=op,
                              nsteps=self.nsteps, shift=self.shift)


def _masked_quantile(vals: jax.Array, mask: jax.Array, q) -> jax.Array:
    """Quantile along the last axis ignoring masked entries (sort-based,
    linear interpolation, matching Prometheus quantile semantics)."""
    big = jnp.where(mask, vals, jnp.inf)
    svals = jnp.sort(big, axis=-1)
    n = jnp.sum(mask, axis=-1)
    fv = vals.dtype
    pos = q * (n.astype(fv) - 1)
    lo_i = jnp.clip(jnp.floor(pos).astype(jnp.int32), 0, vals.shape[-1] - 1)
    hi_i = jnp.clip(lo_i + 1, 0, vals.shape[-1] - 1)
    frac = pos - lo_i.astype(fv)
    lo_v = jnp.take_along_axis(svals, lo_i[..., None], axis=-1)[..., 0]
    hi_v = jnp.take_along_axis(svals, jnp.minimum(hi_i, jnp.maximum(n - 1, 0))[..., None],
                               axis=-1)[..., 0]
    return lo_v + (hi_v - lo_v) * frac


def _holt_winters(vals: jax.Array, mask: jax.Array, sf, tf) -> jax.Array:
    """Holt-Winters double exponential smoothing over each window.

    sf = smoothing factor, tf = trend factor (both in (0,1)); sequential over
    the ≤ maxw window via lax.scan (reference:
    src/promql/src/functions/holt_winters.rs)."""
    x0 = vals[..., 0]
    x1 = jnp.where(mask[..., 1], vals[..., 1], x0)
    s0, b0 = x1, x1 - x0

    def step(carry, xm):
        s, b = carry
        x, m = xm
        s_new = sf * x + (1 - sf) * (s + b)
        b_new = tf * (s_new - s) + (1 - tf) * b
        s = jnp.where(m, s_new, s)
        b = jnp.where(m, b_new, b)
        return (s, b), None

    xs = jnp.moveaxis(vals[..., 2:], -1, 0)
    ms = jnp.moveaxis(mask[..., 2:], -1, 0)
    (s_fin, _), _ = jax.lax.scan(step, (s0, b0), (xs, ms))
    return s_fin


def instant_select(ts2d, val2d, t0, step, lookback_ms, *, nsteps: int
                   ) -> Tuple[jax.Array, jax.Array]:
    """InstantManipulate (host int64 ts auto-rebased, see
    `range_aggregate_cumsum`)."""
    ts2d, t0 = _rebase_i64_host(ts2d, t0, step, nsteps, lookback_ms)
    return _instant_select(ts2d, val2d, t0, step, lookback_ms, nsteps=nsteps)


@functools.partial(jax.jit, static_argnames=("nsteps",))
def _instant_select(ts2d: jax.Array, val2d: jax.Array,
                    t0, step, lookback_ms, *, nsteps: int
                    ) -> Tuple[jax.Array, jax.Array]:
    """InstantManipulate: at each step pick the latest sample within the
    lookback window [t - lookback, t] (reference:
    src/promql/src/extension_plan/instant_manipulate.rs:46)."""
    S, L = ts2d.shape
    step_ends = t0 + jnp.arange(nsteps, dtype=ts2d.dtype) * step
    ss = jax.vmap(lambda row, v: jnp.searchsorted(row, v, side="right"),
                  in_axes=(0, None))
    hi = ss(ts2d, step_ends).astype(jnp.int32)
    hi1 = jnp.maximum(hi - 1, 0)
    last_t = _gather(ts2d, hi1)
    ok = (hi >= 1) & (last_t >= step_ends[None, :] - lookback_ms)
    return _gather(val2d, hi1), ok
