from .dictionary import Dictionary  # noqa: F401
