"""Host-side dictionary encoding for tag columns.

TPUs (and XLA generally) are hostile to string processing and dynamic hash
tables, so tag values are dictionary-encoded to dense int32 ids on the host
before touching the device. This mirrors the reference's observation that
high-cardinality group-by needs a dictionary/sort strategy rather than a hash
table (SURVEY.md §7 'hard parts'); the reference's row keys live in
src/storage/src/memtable/btree.rs — here the key space is a per-region
insertion-ordered dictionary, which is stable across flushes so SSTs and
memtables agree on ids.
"""

from __future__ import annotations

from typing import Dict, Hashable, Iterable, List, Optional, Sequence

import numpy as np


class Dictionary:
    """Insertion-ordered value <-> dense id mapping."""

    __slots__ = ("_value_to_id", "_values")

    def __init__(self, values: Optional[Iterable[Hashable]] = None):
        self._value_to_id: Dict[Hashable, int] = {}
        self._values: List[Hashable] = []
        if values is not None:
            for v in values:
                self.get_or_insert(v)

    def __len__(self) -> int:
        return len(self._values)

    def get_or_insert(self, value: Hashable) -> int:
        i = self._value_to_id.get(value)
        if i is None:
            i = len(self._values)
            self._value_to_id[value] = i
            self._values.append(value)
        return i

    def get(self, value: Hashable) -> Optional[int]:
        return self._value_to_id.get(value)

    def value(self, i: int) -> Hashable:
        return self._values[i]

    def values(self) -> List[Hashable]:
        return list(self._values)

    def encode(self, values: Sequence[Hashable]) -> np.ndarray:
        """Encode values to int32 ids, inserting unseen values.

        Batches beyond a few hundred rows dedup through np.unique first so
        the per-value dict walk touches each distinct value once — ingest
        batches usually carry few distinct tags (TSBS: 100s of hosts across
        millions of rows). Loader batches additionally present rows grouped
        by tag (sorted ingest order), so a run-collapse pass — encode one
        value per run, np.repeat the ids back out — beats even the hash
        factorize ~5x; a strided sample gates the full adjacency pass so
        shuffled object columns (where elementwise != falls back to
        PyObject compares) never pay for it."""
        n = len(values)
        if n > 256:
            arr = values if isinstance(values, np.ndarray) \
                else np.asarray(values, dtype=object)
            out = self._encode_runs(arr)
            if out is not None:
                return out
            try:
                # hash-based dedup: ~5x faster than sorting on strings
                import pandas as pd
                inv, uniq = pd.factorize(arr, use_na_sentinel=False)
            except (TypeError, ValueError):
                uniq = None      # unhashable values
            if uniq is not None:
                ids_u = np.empty(len(uniq), dtype=np.int32)
                for i, v in enumerate(uniq.tolist()):
                    if isinstance(v, float) and v != v:
                        # factorize surfaces None as NaN; store the real
                        # None so ids stay stable across batches and the
                        # per-value path
                        v = None
                    ids_u[i] = self.get_or_insert(v)
                return ids_u[np.asarray(inv).reshape(-1)] \
                    .astype(np.int32, copy=False)
        out = np.empty(n, dtype=np.int32)
        get = self._value_to_id.get
        for i, v in enumerate(values):
            j = get(v)
            if j is None:
                j = self.get_or_insert(v)
            out[i] = j
        return out

    def _encode_runs(self, arr: np.ndarray) -> Optional[np.ndarray]:
        """Run-collapse fast path: when adjacent rows repeat (series-
        grouped loader batches), encode one value per run. Returns None
        when the sample says runs won't pay, or the values don't support
        vectorized compare."""
        n = len(arr)
        probe = arr[:512]
        try:
            sample_runs = int(np.count_nonzero(probe[1:] != probe[:-1]))
        except Exception:  # noqa: BLE001 — e.g. unhashable/odd objects
            return None
        if sample_runs * 8 > len(probe):     # <8-row runs: not worth a pass
            return None
        flags = np.empty(n, dtype=bool)
        flags[0] = True
        np.not_equal(arr[1:], arr[:-1], out=flags[1:])
        starts = np.nonzero(flags)[0]
        if len(starts) * 16 > n:             # sample lied; fall back
            return None
        run_ids = np.empty(len(starts), dtype=np.int32)
        get = self._value_to_id.get
        for i, v in enumerate(arr[starts].tolist()):
            if isinstance(v, float) and v != v:
                v = None                     # match the factorize path's
            j = get(v)                       # NaN→None normalization
            if j is None:
                j = self.get_or_insert(v)
            run_ids[i] = j
        return np.repeat(run_ids, np.diff(starts, append=n))

    def encode_existing(self, values: Sequence[Hashable]) -> np.ndarray:
        """Encode without inserting; unseen values map to -1."""
        out = np.empty(len(values), dtype=np.int32)
        get = self._value_to_id.get
        for i, v in enumerate(values):
            out[i] = get(v, -1)
        return out

    def decode(self, ids: np.ndarray) -> List[Hashable]:
        vals = self._values
        return [vals[int(i)] for i in ids]

    def to_list(self) -> List[Hashable]:
        return list(self._values)

    @staticmethod
    def from_list(values: List[Hashable]) -> "Dictionary":
        return Dictionary(values)
