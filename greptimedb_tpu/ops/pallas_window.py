"""Pallas TPU kernel for PromQL window-bounds counting.

The hot per-eval computation is `hi[s, k] = #{l : b[s, l] <= k}` — how
many samples of series s fall at or before step k (buckets b are
elementwise-computed from timestamps; see ops/window.py). The XLA
formulation (chunked [S, L, T] compare-reduce) measures ~890ms at the
10k-series × 8192-sample × 1440-step shape on v5e.

MEASURED OUTCOME: this kernel is correct but ~1.3s at the same shape —
slower than XLA. The inner loop's cross-sublane broadcast of each
sample column serializes on the VPU, and Mosaic's "dynamic indices only
on sublanes" rule forbids the layout that would avoid it (every
orientation of this computation needs either a dynamic lane index or a
sublane broadcast). XLA's fused compare-reduce (ops/window.py
_counts_leq_grid) remains the production path; this file stays as the
measured record + the Pallas harness for future kernel work.

Kernel layout (Mosaic only allows dynamic indexing on the sublane axis,
not lanes): inputs arrive TRANSPOSED as b_t [L, S] so the inner loop
walks samples along sublanes; series ride the 128-lane axis; the
accumulator is the transposed output block [T_pad, 128] revisited
across the L grid dimension (last grid dim iterates fastest, so all
L-tiles of one S-tile run consecutively).

`counts_leq_pallas` takes/returns the natural [S, L] / [S, T] layouts
and performs the transposes at the XLA boundary. Tests run the kernel
in interpret mode on CPU; real-TPU use is gated by the caller.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

S_LANES = 128       # series per program (lane axis)
L_TILE = 512        # samples per grid step (sublane axis)


def _kernel(bt_ref, out_ref, *, t_pad: int, l_tile: int):
    from jax.experimental import pallas as pl

    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    ks = jax.lax.broadcasted_iota(jnp.int32, (t_pad, S_LANES), 0)

    def body(l, acc):
        col = bt_ref[l, :]                     # [S_LANES], dynamic sublane
        return acc + (col[None, :] <= ks).astype(jnp.int32)

    out_ref[:] += jax.lax.fori_loop(0, l_tile, body,
                                    jnp.zeros((t_pad, S_LANES), jnp.int32))


@functools.partial(jax.jit, static_argnames=("nsteps", "interpret"))
def counts_leq_pallas(b: jax.Array, nsteps: int,
                      interpret: bool = False) -> jax.Array:
    """hi[s, k] = #(b[s, l] <= k) for k < nsteps; b int32 [S, L] with
    out-of-range samples already clipped to >= nsteps by the caller."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    S, L = b.shape
    t_pad = -(-nsteps // 8) * 8                # sublane multiple
    s_pad = (-S) % S_LANES
    l_pad = (-L) % L_TILE
    if s_pad or l_pad:
        b = jnp.pad(b, ((0, s_pad), (0, l_pad)),
                    constant_values=nsteps)    # pads count into no step
    bt = b.T                                   # [Lp, Sp]
    Lp, Sp = bt.shape

    grid = (Sp // S_LANES, Lp // L_TILE)
    out_t = pl.pallas_call(
        functools.partial(_kernel, t_pad=t_pad, l_tile=L_TILE),
        grid=grid,
        in_specs=[pl.BlockSpec((L_TILE, S_LANES),
                               lambda i, j: (j, i),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((t_pad, S_LANES),
                               lambda i, j: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((t_pad, Sp), jnp.int32),
        interpret=interpret,
    )(bt)
    return out_t.T[:S, :nsteps]
