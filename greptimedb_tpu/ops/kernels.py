"""Core TPU kernels: segment-reduce group-by and sort-based merge/dedup.

These are the hot loops of the database. In the reference they are:
- DataFusion's hash aggregate (src/query executes via DataFusion) → here a
  dictionary-encoded **segment reduce** (`jax.ops.segment_sum/min/max`) over
  dense group ids, which XLA lowers to efficient scatter-adds and which
  composes with time-bucketing by id arithmetic (gid = tag_id * nbuckets + b).
- The k-way MergeReader + DedupReader (src/storage/src/read/{merge,dedup}.rs,
  ~1.2k lines of comparison-driven CPU code) → here a **sort-based merge**:
  concatenate runs, `lexsort` by (series, ts, seq), and a vectorized keep-mask
  (last sequence per (series, ts) wins, DELETEs drop the key) — the pragmatic
  TPU answer from SURVEY.md §7.

Everything is static-shaped: batches are padded to shape buckets (powers of
two) with a validity mask so XLA compiles once per bucket, not per batch.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# op_type values in the storage engine (mirrors reference OpType:
# src/store-api/src/storage/requests.rs — Put/Delete).
OP_PUT = 0
OP_DELETE = 1

AGG_OPS = ("sum", "count", "avg", "min", "max", "first", "last",
           "stddev", "variance")


def shape_bucket(n: int, minimum: int = 1024) -> int:
    """Round n up to a power of two (>= minimum) to bound recompilations."""
    if n <= minimum:
        return minimum
    return 1 << (n - 1).bit_length()


def pad_axis0(arr: np.ndarray, target: int, fill=0) -> np.ndarray:
    n = arr.shape[0]
    if n == target:
        return arr
    pad = np.full((target - n,) + arr.shape[1:], fill, dtype=arr.dtype)
    return np.concatenate([arr, pad], axis=0)


def check_i64_safe(*arrays, what: str = "timestamps") -> None:
    """Guard against silent int64→int32 truncation.

    With jax_enable_x64 off (the default, and the norm on TPU), jnp.asarray
    silently narrows int64 host arrays to int32 — epoch-ms timestamps wrap
    negative and dedup/window logic returns wrong answers. Callers must
    rebase such values (e.g. to region-relative offsets) before the device.
    """
    import jax as _jax
    if _jax.config.jax_enable_x64:
        return
    lim = np.iinfo(np.int32)
    for a in arrays:
        if isinstance(a, np.ndarray) and a.dtype == np.int64 and a.size:
            mx, mn = int(a.max()), int(a.min())
            if mx > lim.max or mn < lim.min:
                raise ValueError(
                    f"{what} exceed int32 range ({mn}..{mx}) and x64 is "
                    f"disabled: rebase to region-relative offsets before "
                    f"device transfer (see SeriesMatrix.device_arrays)")


# ---------------------------------------------------------------------------
# Grouped aggregation
# ---------------------------------------------------------------------------

def grouped_aggregate(gids, mask, ts, values, col_masks=(), *, num_groups,
                      ops, has_col_masks=False):
    """Host-validating wrapper around the jitted kernel (see below).

    Rejects int64 inputs that would silently truncate when x64 is off."""
    check_i64_safe(ts, what="grouped_aggregate ts")
    check_i64_safe(*[v for v in values], what="grouped_aggregate values")
    return _grouped_aggregate(gids, mask, ts, tuple(values), tuple(col_masks),
                              num_groups=num_groups, ops=tuple(ops),
                              has_col_masks=has_col_masks)


@functools.partial(jax.jit, static_argnames=("num_groups", "ops", "has_col_masks"))
def _grouped_aggregate(
    gids: jax.Array,            # int32 [N] group id per row (invalid rows: any)
    mask: jax.Array,            # bool  [N] row validity (filter & padding)
    ts: jax.Array,              # int64/int32 [N] timestamps (for first/last)
    values: Tuple[jax.Array, ...],   # per-agg value column [N]
    col_masks: Tuple[jax.Array, ...] = (),  # per-agg column validity [N]
    *,
    num_groups: int,
    ops: Tuple[str, ...],
    has_col_masks: bool = False,
) -> Tuple[Tuple[jax.Array, ...], jax.Array]:
    """Fused masked group-by aggregation.

    `mask` is the row-level filter (predicates & padding); `col_masks`, when
    provided, add per-aggregation column validity (SQL null semantics: a null
    in one column must not hide the row from other aggregates).

    Returns (per-op result arrays [num_groups], group row-count [num_groups]).
    Empty groups yield 0 for sum/count and NaN for avg/min/max/first/last;
    callers null them out via the returned counts.
    """
    n = gids.shape[0]
    # Route masked-out rows to a scratch group so they never pollute results.
    safe_gids = jnp.where(mask, gids, num_groups)
    seg = num_groups + 1
    counts_all = jax.ops.segment_sum(mask.astype(jnp.int32), safe_gids,
                                     num_segments=seg)
    counts = counts_all[:num_groups]

    def agg_mask(i):
        if has_col_masks:
            return mask & col_masks[i]
        return mask

    results = []
    cache: Dict[Tuple[str, int], jax.Array] = {}

    def seg_sum(col, key, m):
        k = ("sum", key)
        if k not in cache:
            cache[k] = jax.ops.segment_sum(
                jnp.where(m, col, 0).astype(col.dtype), safe_gids,
                num_segments=seg)[:num_groups]
        return cache[k]

    def seg_count(m, key):
        k = ("count", key)
        if k not in cache:
            if not has_col_masks:
                cache[k] = counts
            else:
                cache[k] = jax.ops.segment_sum(
                    m.astype(jnp.int32), safe_gids, num_segments=seg)[:num_groups]
        return cache[k]

    for i, op in enumerate(ops):
        col = values[i]
        m = agg_mask(i)
        if op == "count":
            results.append(seg_count(m, i))
        elif op == "sum":
            results.append(seg_sum(col, i, m))
        elif op == "avg":
            s = seg_sum(col, i, m)
            c = seg_count(m, i)
            results.append(jnp.where(c > 0, s / jnp.maximum(c, 1), jnp.nan))
        elif op in ("stddev", "variance"):
            s = seg_sum(col, i, m)
            sq = jax.ops.segment_sum(
                jnp.where(m, col * col, 0), safe_gids, num_segments=seg)[:num_groups]
            c = jnp.maximum(seg_count(m, i), 1)
            var = jnp.maximum(sq / c - (s / c) ** 2, 0.0)
            results.append(jnp.sqrt(var) if op == "stddev" else var)
        elif op == "min":
            filled = jnp.where(m, col, _max_ident(col.dtype))
            r = jax.ops.segment_min(filled, safe_gids, num_segments=seg)[:num_groups]
            results.append(r)
        elif op == "max":
            filled = jnp.where(m, col, _min_ident(col.dtype))
            r = jax.ops.segment_max(filled, safe_gids, num_segments=seg)[:num_groups]
            results.append(r)
        elif op in ("first", "last"):
            # two-pass arg-extreme: find the extreme ts per group, then the
            # first row index achieving it, then gather the value.
            if op == "first":
                ext_ts = jax.ops.segment_min(
                    jnp.where(m, ts, _max_ident(ts.dtype)), safe_gids,
                    num_segments=seg)
            else:
                ext_ts = jax.ops.segment_max(
                    jnp.where(m, ts, _min_ident(ts.dtype)), safe_gids,
                    num_segments=seg)
            hit = m & (ts == ext_ts[safe_gids])
            idx = jax.ops.segment_min(
                jnp.where(hit, jnp.arange(n, dtype=jnp.int32), n), safe_gids,
                num_segments=seg)[:num_groups]
            safe_idx = jnp.minimum(idx, n - 1)
            # dtype-preserving null fill: NaN for floats, 0 for ints (callers
            # null empty groups via the returned counts)
            empty = jnp.nan if jnp.issubdtype(col.dtype, jnp.floating) \
                else jnp.zeros((), col.dtype)
            results.append(jnp.where(idx < n, col[safe_idx], empty))
        else:
            raise ValueError(f"unsupported agg op: {op}")
    return tuple(results), counts


def _max_ident(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf, dtype)
    return jnp.array(jnp.iinfo(dtype).max, dtype)


def _min_ident(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(-jnp.inf, dtype)
    return jnp.array(jnp.iinfo(dtype).min, dtype)


def time_bucket_ids(ts: jax.Array, origin: int, stride: int,
                    num_buckets: int) -> jax.Array:
    """Map timestamps onto [0, num_buckets) bucket ids (clamped)."""
    b = (ts - origin) // stride
    return jnp.clip(b, 0, num_buckets - 1).astype(jnp.int32)


def combine_group_ids(tag_gids: jax.Array, bucket_ids: jax.Array,
                      num_buckets: int) -> jax.Array:
    return (tag_gids.astype(jnp.int32) * num_buckets
            + bucket_ids.astype(jnp.int32))


# ---------------------------------------------------------------------------
# Sort-based merge + dedup
# ---------------------------------------------------------------------------

def sort_merge_dedup(series_ids, ts, seq, op_types, valid):
    """Host-validating wrapper: rejects int64 ts/seq that would silently
    truncate when x64 is off (rebase timestamps first)."""
    check_i64_safe(ts, what="sort_merge_dedup ts")
    check_i64_safe(seq, what="sort_merge_dedup seq")
    return _sort_merge_dedup(series_ids, ts, seq, op_types, valid)


@jax.jit
def _sort_merge_dedup(series_ids: jax.Array,  # int32 [N]
                      ts: jax.Array,          # int[N] (rebased if x64 off)
                      seq: jax.Array,         # int [N] write sequence
                      op_types: jax.Array,    # int8  [N] OP_PUT / OP_DELETE
                      valid: jax.Array,       # bool  [N] padding mask
                      ) -> Tuple[jax.Array, jax.Array]:
    """Merge-sort rows from any number of concatenated runs and compute the
    MVCC keep-mask.

    Returns (order, keep): `order` is the permutation sorting rows by
    (series, ts, seq) with invalid rows last; `keep[i]` marks, in sorted
    position i, rows that survive dedup — the highest sequence for each
    (series, ts) key, unless that winner is a DELETE.
    """
    n = series_ids.shape[0]
    big_series = jnp.where(valid, series_ids, jnp.iinfo(jnp.int32).max)
    order = jnp.lexsort((seq, ts, big_series))
    s_sorted = big_series[order]
    t_sorted = ts[order]
    op_sorted = op_types[order]
    v_sorted = valid[order]
    # last row of each (series, ts) run wins (seq ascending within run)
    nxt_same = jnp.concatenate([
        (s_sorted[1:] == s_sorted[:-1]) & (t_sorted[1:] == t_sorted[:-1]),
        jnp.array([False]),
    ])
    keep = v_sorted & (~nxt_same) & (op_sorted == OP_PUT)
    return order, keep


def merge_dedup_numpy(series_ids: np.ndarray, ts: np.ndarray, seq: np.ndarray,
                      op_types: np.ndarray) -> np.ndarray:
    """Host/NumPy twin of sort_merge_dedup returning kept row indices in
    (series, ts) order — used by the flush path and as the test oracle."""
    order = np.lexsort((seq, ts, series_ids))
    s, t, o = series_ids[order], ts[order], op_types[order]
    nxt_same = np.concatenate([(s[1:] == s[:-1]) & (t[1:] == t[:-1]), [False]])
    keep = (~nxt_same) & (o == OP_PUT)
    return order[keep]


# ---------------------------------------------------------------------------
# Filter program → mask (compiled per query structure)
# ---------------------------------------------------------------------------

CMP_OPS = {"eq", "ne", "lt", "le", "gt", "ge", "isin", "between"}


def apply_cmp(op: str, col: jax.Array, a, b=None) -> jax.Array:
    if op == "eq":
        return col == a
    if op == "ne":
        return col != a
    if op == "lt":
        return col < a
    if op == "le":
        return col <= a
    if op == "gt":
        return col > a
    if op == "ge":
        return col >= a
    if op == "between":
        return (col >= a) & (col <= b)
    if op == "isin":
        return jnp.isin(col, a)
    raise ValueError(f"unknown cmp op {op}")
