"""Core TPU kernels: segment-reduce group-by and sort-based merge/dedup.

These are the hot loops of the database. In the reference they are:
- DataFusion's hash aggregate (src/query executes via DataFusion) → here a
  dictionary-encoded **segment reduce** (`jax.ops.segment_sum/min/max`) over
  dense group ids, which XLA lowers to efficient scatter-adds and which
  composes with time-bucketing by id arithmetic (gid = tag_id * nbuckets + b).
- The k-way MergeReader + DedupReader (src/storage/src/read/{merge,dedup}.rs,
  ~1.2k lines of comparison-driven CPU code) → here a **sort-based merge**:
  concatenate runs, `lexsort` by (series, ts, seq), and a vectorized keep-mask
  (last sequence per (series, ts) wins, DELETEs drop the key) — the pragmatic
  TPU answer from SURVEY.md §7.

Everything is static-shaped: batches are padded to shape buckets (powers of
two) with a validity mask so XLA compiles once per bucket, not per batch.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# op_type values in the storage engine (mirrors reference OpType:
# src/store-api/src/storage/requests.rs — Put/Delete).
OP_PUT = 0
OP_DELETE = 1

AGG_OPS = ("sum", "count", "avg", "min", "max", "first", "last",
           "stddev", "variance")


def shape_bucket(n: int, minimum: int = 1024) -> int:
    """Round n up to a power of two (>= minimum) to bound recompilations."""
    if n <= minimum:
        return minimum
    return 1 << (n - 1).bit_length()


def pad_axis0(arr: np.ndarray, target: int, fill=0) -> np.ndarray:
    n = arr.shape[0]
    if n == target:
        return arr
    pad = np.full((target - n,) + arr.shape[1:], fill, dtype=arr.dtype)
    return np.concatenate([arr, pad], axis=0)


def check_i64_safe(*arrays, what: str = "timestamps") -> None:
    """Guard against silent int64→int32 truncation.

    With jax_enable_x64 off (the default, and the norm on TPU), jnp.asarray
    silently narrows int64 host arrays to int32 — epoch-ms timestamps wrap
    negative and dedup/window logic returns wrong answers. Callers must
    rebase such values (e.g. to region-relative offsets) before the device.
    """
    import jax as _jax
    if _jax.config.jax_enable_x64:
        return
    lim = np.iinfo(np.int32)
    for a in arrays:
        if isinstance(a, np.ndarray) and a.dtype == np.int64 and a.size:
            mx, mn = int(a.max()), int(a.min())
            if mx > lim.max or mn < lim.min:
                raise ValueError(
                    f"{what} exceed int32 range ({mn}..{mx}) and x64 is "
                    f"disabled: rebase to region-relative offsets before "
                    f"device transfer (see SeriesMatrix.device_arrays)")


# ---------------------------------------------------------------------------
# Grouped aggregation
# ---------------------------------------------------------------------------

def grouped_aggregate(gids, mask, ts, values, col_masks=(), *, num_groups,
                      ops, has_col_masks=False):
    """Host-validating wrapper around the jitted kernel (see below).

    Rejects int64 inputs that would silently truncate when x64 is off."""
    check_i64_safe(ts, what="grouped_aggregate ts")
    check_i64_safe(*[v for v in values], what="grouped_aggregate values")
    return _grouped_aggregate(gids, mask, ts, tuple(values), tuple(col_masks),
                              num_groups=num_groups, ops=tuple(ops),
                              has_col_masks=has_col_masks)


@functools.partial(jax.jit, static_argnames=("num_groups", "ops", "has_col_masks"))
def _grouped_aggregate(
    gids: jax.Array,            # int32 [N] group id per row (invalid rows: any)
    mask: jax.Array,            # bool  [N] row validity (filter & padding)
    ts: jax.Array,              # int64/int32 [N] timestamps (for first/last)
    values: Tuple[jax.Array, ...],   # per-agg value column [N]
    col_masks: Tuple[jax.Array, ...] = (),  # per-agg column validity [N]
    *,
    num_groups: int,
    ops: Tuple[str, ...],
    has_col_masks: bool = False,
) -> Tuple[Tuple[jax.Array, ...], jax.Array]:
    """Fused masked group-by aggregation.

    `mask` is the row-level filter (predicates & padding); `col_masks`, when
    provided, add per-aggregation column validity (SQL null semantics: a null
    in one column must not hide the row from other aggregates).

    Returns (per-op result arrays [num_groups], group row-count [num_groups]).
    Empty groups yield 0 for sum/count and NaN for avg/min/max/first/last;
    callers null them out via the returned counts.
    """
    n = gids.shape[0]
    # Route masked-out rows to a scratch group so they never pollute results.
    safe_gids = jnp.where(mask, gids, num_groups)
    seg = num_groups + 1
    counts_all = jax.ops.segment_sum(mask.astype(jnp.int32), safe_gids,
                                     num_segments=seg)
    counts = counts_all[:num_groups]

    def agg_mask(i):
        if has_col_masks:
            return mask & col_masks[i]
        return mask

    results = []
    cache: Dict[Tuple[str, int], jax.Array] = {}

    def seg_sum(col, key, m):
        k = ("sum", key)
        if k not in cache:
            cache[k] = jax.ops.segment_sum(
                jnp.where(m, col, 0).astype(col.dtype), safe_gids,
                num_segments=seg)[:num_groups]
        return cache[k]

    def seg_count(m, key):
        k = ("count", key)
        if k not in cache:
            if not has_col_masks:
                cache[k] = counts
            else:
                cache[k] = jax.ops.segment_sum(
                    m.astype(jnp.int32), safe_gids, num_segments=seg)[:num_groups]
        return cache[k]

    for i, op in enumerate(ops):
        col = values[i]
        m = agg_mask(i)
        if op == "count":
            results.append(seg_count(m, i))
        elif op == "sum":
            results.append(seg_sum(col, i, m))
        elif op == "avg":
            s = seg_sum(col, i, m)
            c = seg_count(m, i)
            results.append(jnp.where(c > 0, s / jnp.maximum(c, 1), jnp.nan))
        elif op in ("stddev", "variance"):
            # Shifted one-pass moments: center on the column's global mean
            # before squaring (variance is shift-invariant). Squaring raw
            # values wraps int columns and loses the variance of large,
            # tight distributions to f32 cancellation; centering fixes both.
            colf = col.astype(jnp.promote_types(col.dtype, jnp.float32))
            c = seg_count(m, i)
            gc = jnp.maximum(jnp.sum(c), 1)
            shift = jnp.sum(jnp.where(m, colf, 0.0)) / gc
            d = jnp.where(m, colf - shift, 0.0)
            s = jax.ops.segment_sum(d, safe_gids,
                                    num_segments=seg)[:num_groups]
            sq = jax.ops.segment_sum(d * d, safe_gids,
                                     num_segments=seg)[:num_groups]
            cc = jnp.maximum(c, 1)
            # sample variance (ddof=1, DataFusion convention); <2 rows → NaN
            var = jnp.maximum(sq - (s / cc) * s, 0.0) / jnp.maximum(c - 1, 1)
            var = jnp.where(c >= 2, var, jnp.nan)
            results.append(jnp.sqrt(var) if op == "stddev" else var)
        elif op == "min":
            filled = jnp.where(m, col, _max_ident(col.dtype))
            r = jax.ops.segment_min(filled, safe_gids, num_segments=seg)[:num_groups]
            results.append(r)
        elif op == "max":
            filled = jnp.where(m, col, _min_ident(col.dtype))
            r = jax.ops.segment_max(filled, safe_gids, num_segments=seg)[:num_groups]
            results.append(r)
        elif op in ("first", "last"):
            # two-pass arg-extreme: find the extreme ts per group, then the
            # first row index achieving it, then gather the value.
            if op == "first":
                ext_ts = jax.ops.segment_min(
                    jnp.where(m, ts, _max_ident(ts.dtype)), safe_gids,
                    num_segments=seg)
            else:
                ext_ts = jax.ops.segment_max(
                    jnp.where(m, ts, _min_ident(ts.dtype)), safe_gids,
                    num_segments=seg)
            hit = m & (ts == ext_ts[safe_gids])
            idx = jax.ops.segment_min(
                jnp.where(hit, jnp.arange(n, dtype=jnp.int32), n), safe_gids,
                num_segments=seg)[:num_groups]
            safe_idx = jnp.minimum(idx, n - 1)
            # dtype-preserving null fill: NaN for floats, 0 for ints (callers
            # null empty groups via the returned counts)
            empty = jnp.nan if jnp.issubdtype(col.dtype, jnp.floating) \
                else jnp.zeros((), col.dtype)
            results.append(jnp.where(idx < n, col[safe_idx], empty))
        else:
            raise ValueError(f"unsupported agg op: {op}")
    return tuple(results), counts


def _max_ident(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf, dtype)
    return jnp.array(jnp.iinfo(dtype).max, dtype)


def _min_ident(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(-jnp.inf, dtype)
    return jnp.array(jnp.iinfo(dtype).min, dtype)


def time_bucket_ids(ts: jax.Array, origin: int, stride: int,
                    num_buckets: int) -> jax.Array:
    """Map timestamps onto [0, num_buckets) bucket ids (clamped)."""
    b = (ts - origin) // stride
    return jnp.clip(b, 0, num_buckets - 1).astype(jnp.int32)


def combine_group_ids(tag_gids: jax.Array, bucket_ids: jax.Array,
                      num_buckets: int) -> jax.Array:
    return (tag_gids.astype(jnp.int32) * num_buckets
            + bucket_ids.astype(jnp.int32))


# ---------------------------------------------------------------------------
# Sort-based merge + dedup
# ---------------------------------------------------------------------------

def sort_merge_dedup(series_ids, ts, seq, op_types, valid):
    """Host-validating wrapper: rejects int64 ts/seq that would silently
    truncate when x64 is off (rebase timestamps first)."""
    check_i64_safe(ts, what="sort_merge_dedup ts")
    check_i64_safe(seq, what="sort_merge_dedup seq")
    return _sort_merge_dedup(series_ids, ts, seq, op_types, valid)


@jax.jit
def _sort_merge_dedup(series_ids: jax.Array,  # int32 [N]
                      ts: jax.Array,          # int[N] (rebased if x64 off)
                      seq: jax.Array,         # int [N] write sequence
                      op_types: jax.Array,    # int8  [N] OP_PUT / OP_DELETE
                      valid: jax.Array,       # bool  [N] padding mask
                      ) -> Tuple[jax.Array, jax.Array]:
    """Merge-sort rows from any number of concatenated runs and compute the
    MVCC keep-mask.

    Returns (order, keep): `order` is the permutation sorting rows by
    (series, ts, seq) with invalid rows last; `keep[i]` marks, in sorted
    position i, rows that survive dedup — the highest sequence for each
    (series, ts) key, unless that winner is a DELETE.
    """
    n = series_ids.shape[0]
    big_series = jnp.where(valid, series_ids, jnp.iinfo(jnp.int32).max)
    order = jnp.lexsort((seq, ts, big_series))
    s_sorted = big_series[order]
    t_sorted = ts[order]
    op_sorted = op_types[order]
    v_sorted = valid[order]
    # last row of each (series, ts) run wins (seq ascending within run)
    nxt_same = jnp.concatenate([
        (s_sorted[1:] == s_sorted[:-1]) & (t_sorted[1:] == t_sorted[:-1]),
        jnp.array([False]),
    ])
    keep = v_sorted & (~nxt_same) & (op_sorted == OP_PUT)
    return order, keep


def _merge_order(s: np.ndarray, t: np.ndarray, q: np.ndarray) -> np.ndarray:
    """Permutation sorting rows by (series, ts, seq).

    Fast path: pack (sid, ts - ts_min) into ONE uint64 key and radix-sort
    it (np stable argsort on ints) — ~5x faster than the 3-key lexsort on
    multi-million-row slices. Stable order keeps input order within equal
    (sid, ts) keys, so the rare duplicate clusters are re-ordered by seq
    exactly afterwards; wide domains fall back to lexsort."""
    n = len(s)
    if n <= 1:
        return np.arange(n, dtype=np.intp)
    smin = int(s.min())
    sbits = max(int(int(s.max()) - smin).bit_length(), 1)
    tmin = int(t.min())
    tbits = max(int(int(t.max()) - tmin).bit_length(), 1)
    if sbits + tbits > 64:
        return np.lexsort((q, t, s))
    key = ((s.astype(np.int64) - smin).astype(np.uint64)
           << np.uint64(tbits)) | (t - tmin).astype(np.uint64)
    order = np.argsort(key, kind="stable")
    k_sorted = key[order]
    dup = k_sorted[1:] == k_sorted[:-1]
    if dup.any():
        # positions participating in an equal-key cluster (MVCC versions
        # of one (sid, ts)): sort that tiny subset by (key, seq)
        member = np.concatenate([[False], dup]) | \
            np.concatenate([dup, [False]])
        idx = np.nonzero(member)[0]
        sub = order[idx]
        order[idx] = sub[np.lexsort((q[sub], k_sorted[idx]))]
    return order


def merge_dedup_numpy(series_ids: np.ndarray, ts: np.ndarray, seq: np.ndarray,
                      op_types: np.ndarray, *,
                      keep_deletes: bool = False) -> np.ndarray:
    """Host/NumPy twin of sort_merge_dedup returning kept row indices in
    (series, ts) order — used by the flush path and as the test oracle.

    keep_deletes=True keeps the newest row per key even when it is a delete
    tombstone (compaction must preserve tombstones that shadow older files
    outside the merge set)."""
    order = _merge_order(series_ids, ts, seq)
    s, t, o = series_ids[order], ts[order], op_types[order]
    nxt_same = np.concatenate([(s[1:] == s[:-1]) & (t[1:] == t[:-1]), [False]])
    keep = ~nxt_same if keep_deletes else (~nxt_same) & (o == OP_PUT)
    return order[keep]


# ---------------------------------------------------------------------------
# Filter program → mask (compiled per query structure)
# ---------------------------------------------------------------------------

CMP_OPS = {"eq", "ne", "lt", "le", "gt", "ge", "isin", "between"}


def apply_cmp(op: str, col: jax.Array, a, b=None) -> jax.Array:
    if op == "eq":
        return col == a
    if op == "ne":
        return col != a
    if op == "lt":
        return col < a
    if op == "le":
        return col <= a
    if op == "gt":
        return col > a
    if op == "ge":
        return col >= a
    if op == "between":
        return (col >= a) & (col <= b)
    if op == "isin":
        return jnp.isin(col, a)
    raise ValueError(f"unknown cmp op {op}")


# ---------------------------------------------------------------------------
# Sorted-segment group-by (the LSM fast path)
# ---------------------------------------------------------------------------
# Post-merge scan data is sorted by (series, ts), so (series, time-bucket)
# group ids are non-decreasing — group-by becomes contiguous-segment
# reduction, with no scatter at all (XLA scatter serializes on TPU; measured
# ~100x slower than this path on v5e). Structure per segment [s, e):
#   inner:  whole 1024-row blocks — per-block partials (one bandwidth pass)
#           combined by prefix-sum difference (sum family) or an RMQ sparse
#           table over block partials (min/max family);
#   edges:  the two partial blocks — fixed-size masked gather windows.
# Two-level sums also bound float32 error: naive full-array cumsum boundary
# differences lose ~N*eps of the running prefix; per-block partials keep
# absolute error at ~block*eps + NB*eps of block sums.

# Mini-block size: edge windows gather [num_groups, 2*block] elements, and
# TPU scalar gather is ~20ns/element — small blocks keep edges cheap while
# the sparse table over mini partials keeps inner ranges O(1) per group.
# (Measured on v5e: block=1024 → 128 ms for a 5-col avg over 16.7M rows,
# all in edge gathers; block=32 → gathers drop 32x and the pass is
# bandwidth-bound.)
_SEG_BLOCK = 32


def _edge_windows(x, starts, ends, bs, be, ident, n):
    """Gather the two ≤block-sized partial-block windows of each segment,
    ident-filled outside [start, end) — [G, 2*block] per group."""
    B = _SEG_BLOCK
    ar = jnp.arange(B, dtype=jnp.int32)
    # left partial block: [s, min(e, bs*B)); right partial: [max(s, be*B), e)
    lidx = starts[:, None] + ar[None, :]
    lhi = jnp.minimum(ends, bs * B)
    lvalid = lidx < lhi[:, None]
    ridx = (be * B)[:, None] + ar[None, :]
    rvalid = (ridx >= starts[:, None]) & (ridx < ends[:, None])
    lv = jnp.where(lvalid, x[jnp.minimum(lidx, n - 1)], ident)
    rv = jnp.where(rvalid, x[jnp.minimum(ridx, n - 1)], ident)
    return jnp.concatenate([lv, rv], axis=1)


def _segment_bounds(gids, num_groups, n):
    # For dense integer queries, left-search at g equals right-search at
    # g-1, so starts is a shift of ends — one searchsorted, not two (the
    # binary search is the gather-bound cost at high cardinality). Requires
    # non-negative gids (starts[0] = 0), the contract of this module.
    ar = jnp.arange(num_groups, dtype=gids.dtype)
    ends = jnp.searchsorted(gids, ar, side="right").astype(jnp.int32)
    starts = jnp.concatenate([jnp.zeros(1, jnp.int32), ends[:-1]])
    return (starts, ends) + _block_cover(starts, ends)


def _block_cover(starts, ends):
    B = _SEG_BLOCK
    bs = (starts + B - 1) // B        # first fully-covered block
    be = ends // B                    # one past last fully-covered block
    # when the segment lives inside one block, there are no inner blocks
    has_inner = be > bs
    return bs, be, has_inner


def _pad_block(x, ident, n):
    pad = (-n) % _SEG_BLOCK
    if pad:
        x = jnp.concatenate([x, jnp.full((pad,), ident, x.dtype)])
    return x, (n + pad) // _SEG_BLOCK


#: above this group count, segment reductions switch from exact
#: edge-window gathers (O(groups*block), gather-bound at high
#: cardinality) to O(groups)-gather decompositions: sums use a
#: two-level prefix sum; min/max use in-block sparse tables + block
#: suffix/prefix scans. The prefix-sum form can carry ~1-ulp
#: cancellation noise into small segments, so the exact form stays for
#: the common low-cardinality group-bys whose results users read
#: directly.
_SEG_HIGH_CARD_THRESHOLD = 8192


def _sorted_seg_sum(x, starts, ends, bs, be, has_inner, n):
    """Per-segment sum of x (zeros where masked).

    Low cardinality: per-segment block partials + edge windows (exact).
    High cardinality: in-block inclusive scans + cumsum over block sums
    form a global prefix P; each segment is P[end]-P[start] — measured
    4-8x faster at 120k-1.2M groups on v5e (the edge-window design is
    O(groups*block) random gather). Bounds always come from dense integer
    group queries (this module's contract), so starts[g] == ends[g-1] and
    the prefix at starts is a shift of the prefix at ends — halving the
    O(G) gather count, the dominant cost at 1M+ groups."""
    if jnp.issubdtype(x.dtype, jnp.integer):
        acc = jnp.promote_types(x.dtype, jnp.int32)  # exact int accumulation
    else:
        acc = jnp.promote_types(x.dtype, jnp.float32)
    B = _SEG_BLOCK
    num_groups = starts.shape[0]
    if num_groups <= _SEG_HIGH_CARD_THRESHOLD and \
            not jnp.issubdtype(x.dtype, jnp.integer):
        xp, nb = _pad_block(x.astype(acc), 0, n)
        block_sums = xp.reshape(nb, B).sum(axis=1)
        csum = jnp.concatenate([jnp.zeros(1, acc),
                                jnp.cumsum(block_sums)])
        inner = jnp.where(has_inner,
                          csum[be] - csum[jnp.minimum(bs, nb)], 0)
        edges = _edge_windows(
            x.astype(acc), starts, ends,
            jnp.where(has_inner, bs, (starts // B) + 1),
            jnp.where(has_inner, be, starts // B + 1), 0, n)
        return inner + edges.sum(axis=1)

    xp, nb = _pad_block(x.astype(acc), 0, n)
    inblock = jnp.cumsum(xp.reshape(nb, B), axis=1)      # inclusive scans
    block_sums = inblock[:, -1]
    csum = jnp.concatenate([jnp.zeros(1, acc), jnp.cumsum(block_sums)])

    def prefix(idx):
        """Exclusive global prefix at row index idx ∈ [0, nb*B]."""
        b = idx // B
        r = idx % B
        base = csum[b]                      # b == nb only when r == 0
        inb = jnp.where(
            r > 0,
            inblock[jnp.minimum(b, nb - 1), jnp.maximum(r - 1, 0)], 0)
        return base + inb

    pe = prefix(ends)
    ps = jnp.concatenate([jnp.zeros(1, acc), pe[:-1]])
    return pe - ps


def _floor_log2(ln, K):
    """Integral floor(log2(ln)) clamped to [0, K-1]: float32 log2 can round
    up for huge segments (>= ~2^23 blocks), making the RMQ read past the
    segment."""
    k = jnp.zeros_like(ln)
    for j in range(1, min(K, 31)):
        k = k + (ln >= (1 << j)).astype(ln.dtype)
    return jnp.clip(k, 0, K - 1).astype(jnp.int32)


def _sorted_seg_minmax(x, starts, ends, bs, be, has_inner, n, *, is_min):
    red = jnp.minimum if is_min else jnp.maximum
    ident = _max_ident(x.dtype) if is_min else _min_ident(x.dtype)
    xp, nb = _pad_block(x, ident, n)
    bm = xp.reshape(nb, _SEG_BLOCK)
    bm = bm.min(axis=1) if is_min else bm.max(axis=1)     # [NB]
    # sparse table: ST[k][i] = reduce over blocks [i, i + 2^k)
    K = max(1, (nb - 1).bit_length() + 1)
    st = [bm]
    for k in range(1, K):
        shift = 1 << (k - 1)
        prev = st[-1]
        rolled = jnp.concatenate(
            [prev[shift:], jnp.full((min(shift, nb),), ident, prev.dtype)])
        st.append(red(prev, rolled))
    ST = jnp.stack(st)                                    # [K, NB]
    B = _SEG_BLOCK
    num_groups = starts.shape[0]
    if num_groups <= _SEG_HIGH_CARD_THRESHOLD:
        # low cardinality: per-segment edge windows (cheap at small G)
        ln = jnp.maximum(be - bs, 1)
        k = _floor_log2(ln, K)
        lo = jnp.minimum(bs, nb - 1)
        hi = jnp.clip(be - (1 << k), 0, nb - 1)
        inner = red(ST[k, lo], ST[k, hi])
        inner = jnp.where(has_inner, inner, ident)
        edges = _edge_windows(x, starts, ends,
                              jnp.where(has_inner, bs, starts // B + 1),
                              jnp.where(has_inner, be, starts // B + 1),
                              ident, n)
        er = edges.min(axis=1) if is_min else edges.max(axis=1)
        return red(inner, er)

    # high cardinality: [G, 2*block] edge gathers are the bottleneck
    # (O(groups*block) random access). Replace them with in-block
    # prefix/suffix scans plus an in-block sparse table so every segment
    # resolves with a handful of O(G) gathers:
    #   single-block segment  -> two in-block-ST lookups
    #   multi-block segment   -> suffix[left] ∧ block-ST inner ∧ prefix[right]
    blocks2d = xp.reshape(nb, B)
    if is_min:
        pref = jax.lax.cummin(blocks2d, axis=1)
        suff = jax.lax.cummin(blocks2d[:, ::-1], axis=1)[:, ::-1]
    else:
        pref = jax.lax.cummax(blocks2d, axis=1)
        suff = jax.lax.cummax(blocks2d[:, ::-1], axis=1)[:, ::-1]
    K2 = max(1, (B - 1).bit_length() + 1)
    st_in = [blocks2d]
    for k in range(1, K2):
        shift = 1 << (k - 1)
        prev = st_in[-1]
        rolled = jnp.concatenate(
            [prev[:, shift:],
             jnp.full((nb, min(shift, B)), ident, prev.dtype)], axis=1)
        st_in.append(red(prev, rolled))
    STIN = jnp.stack(st_in)                               # [K2, NB, B]

    e1 = jnp.maximum(ends - 1, 0)
    lb = jnp.minimum(starts // B, nb - 1)
    r0 = starts % B
    rb = jnp.minimum(e1 // B, nb - 1)
    r1 = e1 % B
    single = lb == rb

    seg_len = jnp.maximum(ends - starts, 1)               # <= B when single
    k2 = _floor_log2(seg_len, K2)
    single_val = red(STIN[k2, lb, jnp.minimum(r0, B - 1)],
                     STIN[k2, lb, jnp.clip(r1 + 1 - (1 << k2), 0, B - 1)])

    left = suff[lb, jnp.minimum(r0, B - 1)]
    right = pref[rb, r1]
    iln = rb - lb - 1                                     # inner block count
    kin = _floor_log2(jnp.maximum(iln, 1), K)
    ilo = jnp.clip(lb + 1, 0, nb - 1)
    ihi = jnp.clip(rb - (1 << kin), 0, nb - 1)
    inner = jnp.where(iln >= 1, red(ST[kin, ilo], ST[kin, ihi]), ident)
    multi_val = red(red(left, right), inner)

    out = jnp.where(single, single_val, multi_val)
    return jnp.where(ends > starts, out, ident)


def seg_len_bucket(max_len: int) -> int:
    """Static pass-count bucket for the shift-doubling kernels: the
    smallest even k with 2^k >= max_len. Even buckets bound recompiles;
    the kernels' correctness REQUIRES 2^k >= the longest segment, so
    every caller (scan launch, benches, tests) must derive k through
    this one helper."""
    return -(-max(max_len - 1, 1).bit_length() // 2) * 2


def _seg_minmax_doubling(x, gids, starts, ends, ident, *, is_min, k_max):
    """Segmented min/max by shift-doubling: k_max passes of pure
    elementwise work (shift + gid compare + select), no gathers beyond
    the final per-segment pickup. After pass k, y[i] covers
    [i, min(i + 2^k, segment end)); requires 2^k_max >= the longest
    segment (the host caller bucketizes that bound into `k_max`).

    At high cardinality this replaces the in-block sparse table
    (`_sorted_seg_minmax`'s [K2, NB, B] build is n·log B memory traffic;
    the VERDICT r3/r5 kernel gap) with ~k_max linear passes that map to
    the VPU with no random access — the winning shape on TPU, where
    gathers, not FLOPs, priced the old kernel."""
    n = x.shape[0]
    red = jnp.minimum if is_min else jnp.maximum
    y = x
    for k in range(k_max):
        sh = 1 << k
        if sh >= n:
            break
        ys = jnp.concatenate([y[sh:], jnp.full((sh,), ident, y.dtype)])
        gs = jnp.concatenate(
            [gids[sh:], jnp.full((sh,), -1, gids.dtype)])
        y = jnp.where(gs == gids, red(y, ys), y)
    out = y[jnp.minimum(starts, n - 1)]
    return jnp.where(ends > starts, out, ident)


def _seg_argext_doubling(key, gids, starts, ends, ident, *, is_min, k_max):
    """Segmented lexicographic arg-extreme of (key, position) by
    shift-doubling — one fused pass family carrying the (value, pos)
    pair, replacing the old two-pass minmax + O(n) gather formulation
    (first/last at high cardinality). Returns (ext_key, pos); pos = -1
    for empty segments."""
    n = key.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    for k in range(k_max):
        sh = 1 << k
        if sh >= n:
            break
        ks = jnp.concatenate([key[sh:], jnp.full((sh,), ident, key.dtype)])
        ps = jnp.concatenate([pos[sh:], jnp.full((sh,), -1, jnp.int32)])
        gs = jnp.concatenate(
            [gids[sh:], jnp.full((sh,), -1, gids.dtype)])
        if is_min:
            better = (ks < key) | ((ks == key) & (ps < pos))
        else:
            better = (ks > key) | ((ks == key) & (ps > pos))
        take = (gs == gids) & better
        key = jnp.where(take, ks, key)
        pos = jnp.where(take, ps, pos)
    sel = jnp.minimum(starts, n - 1)
    live = ends > starts
    return (jnp.where(live, key[sel], ident),
            jnp.where(live, pos[sel], -1))


def _sorted_seg_argext(x, starts, ends, bs, be, has_inner, n, *, is_min,
                       gids=None):
    """Per-segment lexicographic arg-extreme of (x, position).

    first = row with the smallest (ts, position); last = largest — matching
    grouped_aggregate's ts-extreme semantics even when ts is NOT sorted
    within a segment (e.g. several series collapsed into one GROUP BY key).
    Returns (ext_x, pos); ext_x == ident means the segment had no valid row.
    """
    B = _SEG_BLOCK
    ident = _max_ident(x.dtype) if is_min else _min_ident(x.dtype)
    num_groups = starts.shape[0]
    if gids is not None and num_groups > _SEG_HIGH_CARD_THRESHOLD:
        # two-pass formulation so the cardinality-robust minmax does the
        # heavy lifting: extreme value per segment, then the tie-breaking
        # position (min pos for first / max pos for last) among the rows
        # attaining it, located via one O(n) gather.
        ext = _sorted_seg_minmax(x, starts, ends, bs, be, has_inner, n,
                                 is_min=is_min)
        iota = jnp.arange(n, dtype=jnp.int32)
        hit = x == ext[gids]
        if is_min:
            pos_fill = jnp.where(hit, iota, n)
            pos = _sorted_seg_minmax(pos_fill, starts, ends, bs, be,
                                     has_inner, n, is_min=True)
            pos = jnp.where(pos >= n, -1, pos)
        else:
            pos_fill = jnp.where(hit, iota, -1)
            pos = _sorted_seg_minmax(pos_fill, starts, ends, bs, be,
                                     has_inner, n, is_min=False)
        return ext, pos

    def pick(ta, pa, tb, pb):
        if is_min:
            a_wins = (ta < tb) | ((ta == tb) & (pa <= pb))
        else:
            a_wins = (ta > tb) | ((ta == tb) & (pa >= pb))
        return jnp.where(a_wins, ta, tb), jnp.where(a_wins, pa, pb)

    xp, nb = _pad_block(x, ident, n)
    xb = xp.reshape(nb, B)
    if is_min:
        off = jnp.argmin(xb, axis=1).astype(jnp.int32)   # first occurrence
    else:
        off = (B - 1 - jnp.argmax(xb[:, ::-1], axis=1)).astype(jnp.int32)
    bt = jnp.take_along_axis(xb, off[:, None], axis=1)[:, 0]
    bp = jnp.arange(nb, dtype=jnp.int32) * B + off
    # pair sparse table over mini partials
    K = max(1, (nb - 1).bit_length() + 1)
    st_t, st_p = [bt], [bp]
    for k in range(1, K):
        shift = 1 << (k - 1)
        pt, pp = st_t[-1], st_p[-1]
        rt = jnp.concatenate(
            [pt[shift:], jnp.full((min(shift, nb),), ident, pt.dtype)])
        rp = jnp.concatenate(
            [pp[shift:], jnp.full((min(shift, nb),), -1, pp.dtype)])
        nt, np_ = pick(pt, pp, rt, rp)
        st_t.append(nt)
        st_p.append(np_)
    ST_T, ST_P = jnp.stack(st_t), jnp.stack(st_p)
    ln = jnp.maximum(be - bs, 1)
    k = _floor_log2(ln, K)
    lo = jnp.minimum(bs, nb - 1)
    hi = jnp.clip(be - (1 << k), 0, nb - 1)
    it, ip = pick(ST_T[k, lo], ST_P[k, lo], ST_T[k, hi], ST_P[k, hi])
    it = jnp.where(has_inner, it, ident)
    ip = jnp.where(has_inner, ip, -1)
    # edge windows carry (value, global position) pairs
    ar = jnp.arange(B, dtype=jnp.int32)
    bsx = jnp.where(has_inner, bs, starts // B + 1)
    bex = jnp.where(has_inner, be, starts // B + 1)
    lidx = starts[:, None] + ar[None, :]
    lvalid = lidx < jnp.minimum(ends, bsx * B)[:, None]
    ridx = (bex * B)[:, None] + ar[None, :]
    rvalid = (ridx >= starts[:, None]) & (ridx < ends[:, None])
    widx = jnp.concatenate([lidx, ridx], axis=1)
    wvalid = jnp.concatenate([lvalid, rvalid], axis=1)
    wt = jnp.where(wvalid, x[jnp.minimum(widx, n - 1)], ident)
    if is_min:
        woff = jnp.argmin(wt, axis=1)[:, None]
    else:
        woff = (wt.shape[1] - 1 -
                jnp.argmax(wt[:, ::-1], axis=1))[:, None]
    et = jnp.take_along_axis(wt, woff, axis=1)[:, 0]
    ep = jnp.take_along_axis(widx, woff, axis=1)[:, 0]
    ep = jnp.where(et == ident, -1, ep)
    ft, fp = pick(it, ip, et, ep)
    return ft, fp


def sorted_grouped_aggregate(gids, mask, ts, values, col_masks=(), *,
                             num_groups, ops, has_col_masks=False,
                             ends=None, seg_len_k=None):
    """Host-validating wrapper (mirrors grouped_aggregate; gids sorted).

    At high cardinality the device-side binary search for segment bounds is
    the dominant cost (gather-bound, ~1.2s at 1.2M groups / 25M rows on
    v5e). Callers that know the segment layout pass `ends` (int32
    [num_groups], cumulative row count per group — the LSM scan path has
    run boundaries on the host already); otherwise host gids fall back to a
    bincount, and device gids to the on-device binary search."""
    check_i64_safe(ts, what="sorted_grouped_aggregate ts")
    check_i64_safe(*[v for v in values], what="sorted_grouped_aggregate values")
    if ends is None and num_groups > _SEG_HIGH_CARD_THRESHOLD \
            and isinstance(gids, np.ndarray):
        hist = np.bincount(gids, minlength=num_groups)[:num_groups]
        ends = np.cumsum(hist, dtype=np.int64).astype(np.int32)
    if ends is not None:
        return _sorted_grouped_aggregate_pre(
            gids, mask, ts, tuple(values), tuple(col_masks), ends,
            num_groups=num_groups, ops=tuple(ops),
            has_col_masks=has_col_masks, seg_len_k=seg_len_k)
    return _sorted_grouped_aggregate(
        gids, mask, ts, tuple(values), tuple(col_masks),
        num_groups=num_groups, ops=tuple(ops), has_col_masks=has_col_masks)


@functools.partial(jax.jit,
                   static_argnames=("num_groups", "ops", "has_col_masks",
                                    "seg_len_k"))
def _sorted_grouped_aggregate_pre(gids, mask, ts, values, col_masks, ends, *,
                                  num_groups, ops, has_col_masks=False,
                                  seg_len_k=None):
    """_sorted_grouped_aggregate with host-precomputed segment ends.

    seg_len_k (static): ceil-log2 of the longest segment, bucketized by
    the caller — enables the shift-doubling min/max + first/last kernels
    at high cardinality. Callers must only pass it when `gids` holds
    REAL run ids (the scan path ships a dummy when no op needs them).
    """
    ends = jnp.asarray(ends)
    starts = jnp.concatenate([jnp.zeros(1, jnp.int32), ends[:-1]])
    bs, be, has_inner = _block_cover(starts, ends)
    return _sga_body(gids, mask, ts, values, col_masks, starts, ends, bs,
                     be, has_inner, num_groups=num_groups, ops=ops,
                     has_col_masks=has_col_masks, seg_len_k=seg_len_k)


@functools.partial(jax.jit,
                   static_argnames=("num_groups", "ops", "has_col_masks"))
def _sorted_grouped_aggregate(gids, mask, ts, values, col_masks=(), *,
                              num_groups, ops, has_col_masks=False):
    """grouped_aggregate twin requiring non-decreasing gids (the natural
    order of merged LSM scans). Same semantics, scatter-free execution.

    Masked-out rows stay in place (their gid keeps the array sorted) and
    contribute the identity. first/last pick the row with the extreme ts
    (position breaks ties), matching the scatter twin's semantics even when
    ts is not sorted within a segment."""
    n = gids.shape[0]
    starts, ends, bs, be, has_inner = _segment_bounds(gids, num_groups, n)
    return _sga_body(gids, mask, ts, values, col_masks, starts, ends, bs,
                     be, has_inner, num_groups=num_groups, ops=ops,
                     has_col_masks=has_col_masks)


def _sga_body(gids, mask, ts, values, col_masks, starts, ends, bs, be,
              has_inner, *, num_groups, ops, has_col_masks,
              seg_len_k=None):
    use_doubling = seg_len_k is not None and \
        num_groups > _SEG_HIGH_CARD_THRESHOLD
    n = gids.shape[0]

    def agg_mask(i):
        return (mask & col_masks[i]) if has_col_masks else mask

    counts = _sorted_seg_sum(mask.astype(jnp.int32), starts, ends, bs, be,
                             has_inner, n).astype(jnp.int32)

    cache = {}

    def seg_sum(col, m, key, square=False):
        ck = (key, square)
        if ck not in cache:
            if square:
                # square in float: col*col wraps int columns past ~46k
                colf = col.astype(jnp.promote_types(col.dtype, jnp.float32))
                v = colf * colf
            else:
                v = col
            cache[ck] = _sorted_seg_sum(jnp.where(m, v, 0), starts, ends, bs,
                                        be, has_inner, n)
        return cache[ck]

    def seg_count(m, key):
        ck = ("count", key if has_col_masks else -1)
        if ck not in cache:
            cache[ck] = _sorted_seg_sum(m.astype(jnp.int32), starts, ends, bs,
                                        be, has_inner, n)
        return cache[ck]

    results = []
    iota = jnp.arange(n, dtype=jnp.int32)
    for i, op in enumerate(ops):
        col, m = values[i], agg_mask(i)
        fdt = col.dtype
        if op == "count":
            results.append(seg_count(m, i).astype(jnp.int32))
        elif op == "sum":
            results.append(seg_sum(col, m, i).astype(fdt))
        elif op == "sum_sq":
            # partial moment for distributed/merged stddev computation
            results.append(seg_sum(col, m, i, square=True))
        elif op == "avg":
            s, c = seg_sum(col, m, i), seg_count(m, i)
            results.append(jnp.where(c > 0, s / jnp.maximum(c, 1), jnp.nan))
        elif op in ("stddev", "variance"):
            # Shifted one-pass moments (see the scatter twin): center on
            # the global mean before squaring — avoids int wraparound and
            # f32 cancellation on large, tight value distributions.
            colf = col.astype(jnp.promote_types(col.dtype, jnp.float32))
            c = seg_count(m, i)
            gc = jnp.maximum(jnp.sum(c), 1)
            shift = jnp.sum(jnp.where(m, colf, 0.0)) / gc
            d = jnp.where(m, colf - shift, 0.0)
            s = _sorted_seg_sum(d, starts, ends, bs, be, has_inner, n)
            sq = _sorted_seg_sum(d * d, starts, ends, bs, be, has_inner, n)
            cc = jnp.maximum(c, 1)
            # sample variance (ddof=1, DataFusion convention); <2 rows → NaN
            var = jnp.maximum(sq - (s / cc) * s, 0.0) / jnp.maximum(c - 1, 1)
            var = jnp.where(c >= 2, var, jnp.nan)
            results.append(jnp.sqrt(var) if op == "stddev" else var)
        elif op in ("min", "max"):
            is_min = op == "min"
            ident = _max_ident(fdt) if is_min else _min_ident(fdt)
            filled = jnp.where(m, col, ident)
            if use_doubling:
                results.append(_seg_minmax_doubling(
                    filled, gids, starts, ends, ident, is_min=is_min,
                    k_max=seg_len_k))
            else:
                results.append(_sorted_seg_minmax(
                    filled, starts, ends, bs, be, has_inner, n,
                    is_min=is_min))
        elif op in ("first", "last"):
            # arg-extreme by (ts, position) — same semantics as the scatter
            # twin even when ts is unsorted within a segment
            is_min = op == "first"
            ident = _max_ident(ts.dtype) if is_min else _min_ident(ts.dtype)
            key = jnp.where(m, ts, ident)
            if use_doubling:
                ext_t, pos = _seg_argext_doubling(
                    key, gids, starts, ends, ident, is_min=is_min,
                    k_max=seg_len_k)
            else:
                ext_t, pos = _sorted_seg_argext(key, starts, ends, bs, be,
                                                has_inner, n,
                                                is_min=is_min, gids=gids)
            found = (ext_t != ident) & (pos >= 0)
            val = col[jnp.clip(pos, 0, n - 1)]
            empty = jnp.nan if jnp.issubdtype(fdt, jnp.floating) \
                else jnp.zeros((), fdt)
            results.append(jnp.where(found, val, empty))
        else:
            raise ValueError(f"unsupported agg op: {op}")
    return tuple(results), counts
