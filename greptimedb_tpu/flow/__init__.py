"""Continuous rollup flows: streaming downsample with query rewrite.

The TPU-native analog of GreptimeDB's flow engine: `CREATE FLOW` registers
a standing aggregate over a source table; a background (or cooperative)
task folds newly-written rows past a per-region watermark into a rollup
sink table via the sorted-segment reducer (storage/downsample.py); the
query planner transparently re-targets compatible `GROUP BY date_bin`
queries at the 60x-smaller sink (flow/rewrite.py).
"""

from .manager import (FlowAgg, FlowManager, FlowSpec, KvFlowStore,
                      ObjectStoreFlowStore, compile_flow)

__all__ = ["FlowAgg", "FlowManager", "FlowSpec", "KvFlowStore",
           "ObjectStoreFlowStore", "compile_flow"]
