"""Transparent rollup rewrite: serve GROUP BY date_bin from a flow sink.

The read half of the flow subsystem (reference: materialized-view query
rewrite; GreptimeDB serves flows as ordinary tables, the rewrite is this
build's extension). A `GROUP BY date_bin(stride', ts)` aggregate over a
flow's source table is re-targeted at the rollup sink when:

- stride' is a multiple of the flow stride (bucket-aligned origins),
- every GROUP BY key is the time bucket or a tag the flow preserves,
- WHERE touches only preserved tags and bucket-aligned time ranges,
- every aggregate is derivable from the stored columns:
  sum/count/min/max/first/last map 1:1 (count re-sums the stored counts),
  avg derives from a stored sum + count pair.

The rewritten statement then flows through the normal dispatch chain
(device-resident / streamed / CPU) against a table ~stride'/1 smaller;
EXPLAIN and EXPLAIN ANALYZE name the decision as `rollup-rewrite`.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..sql import ast
from ..sql.ast import BinaryOp, Cast, Column, FunctionCall, ObjectName

#: process-global kill switch (SET rollup_rewrite = 0/1) — the
#: differential tests and operators compare against the raw path with it
_ENABLED = [True]

from ..query.planner import _AGG_CANON  # one alias map, not three copies

_DIRECT_OPS = {"sum", "min", "max", "first", "last"}
_INT_TYPE_NAMES = {"Int8", "Int16", "Int32", "Int64",
                   "UInt8", "UInt16", "UInt32", "UInt64"}


def set_enabled(on: bool) -> None:
    _ENABLED[0] = bool(on)


def enabled() -> bool:
    return _ENABLED[0]


@dataclass
class RollupRewrite:
    flow: object                   # FlowSpec
    query: ast.Query               # rewritten, targeting the sink
    sink: str
    note: str                      # EXPLAIN / dispatch detail


def try_rewrite(manager, table, analysis, query: ast.Query, ctx
                ) -> Optional[RollupRewrite]:
    """Return a rewrite of `query` onto a flow sink, or None."""
    if manager is None or not _ENABLED[0]:
        return None
    if not analysis.is_aggregate or query.joins or \
            query.from_ is None or query.from_.name is None:
        return None
    catalog, schema_name, name = ctx.resolve(query.from_.name)
    flows = manager.flows_for_source(catalog, schema_name, name)
    if not flows:
        return None
    # prefer the coarsest compatible flow: biggest row reduction
    for flow in sorted(flows, key=lambda f: -f.stride_ms):
        rw = _rewrite_for(flow, table, analysis, query)
        if rw is not None:
            return rw
    return None


def _rewrite_for(flow, table, a, query: ast.Query
                 ) -> Optional[RollupRewrite]:
    from ..query.expr import expr_name
    from ..query.tpu_exec import (_conjuncts, _match_bucket,
                                  _match_time_pred, _refs)

    schema = table.schema
    tc = schema.timestamp_column
    if tc is None or tc.name != flow.ts_column:
        return None
    ts_name = tc.name
    tag_set = set(flow.tags)

    # GROUP BY: exactly one bucket over ts, every other key a kept tag
    bucket = None
    qtags = set()
    for g in a.group_exprs:
        if isinstance(g, Column) and g.name in tag_set:
            qtags.add(g.name)
            continue
        b = _match_bucket(g, ts_name)
        if b is not None and bucket is None:
            bucket = b
            continue
        return None
    if bucket is None:
        return None
    s = flow.stride_ms
    if bucket.stride_ms % s != 0 or \
            (bucket.origin - flow.origin_ms) % s != 0:
        return None

    # WHERE: preserved tags, or bucket-aligned time ranges
    for c in _conjuncts(query.where):
        refs = _refs(c)
        if refs and refs <= tag_set:
            continue
        if refs == {ts_name}:
            rng = _match_time_pred(c, ts_name)
            if rng is None:
                return None
            lo, hi = rng
            if lo is not None and (lo - flow.origin_ms) % s != 0:
                return None
            if hi is not None and (hi - flow.origin_ms) % s != 0:
                return None
            continue
        return None

    # aggregate derivability: (op, column) -> replacement builder
    by_key: Dict[Tuple[str, Optional[str]], str] = {
        (fa.op, fa.column): fa.dest for fa in flow.aggs}

    def _src_int_type(col: Optional[str]) -> Optional[str]:
        """Source column's integral type name, or None — sink columns
        are FLOAT64, so integer results must cast back (the same rule
        _result_dtype_override applies on the raw path)."""
        if col is None or not schema.contains(col):
            return None
        d = schema.column_schema(col).dtype
        return d.name if d.name in _INT_TYPE_NAMES else None

    def map_call(op: str, col: Optional[str]):
        """Replacement expr for op(col) over the sink, or None."""
        if op == "count":
            dest = by_key.get(("count", col))
            if dest is None:
                return None
            # counts re-sum; cast back so the result stays integral
            return Cast(FunctionCall("sum", [Column(dest)]), "bigint")
        if op in _DIRECT_OPS:
            dest = by_key.get((op, col))
            if dest is None:
                return None
            out = FunctionCall(op, [Column(dest)])
            it = _src_int_type(col)
            if it is not None:
                return Cast(out, "bigint" if op == "sum" else it)
            return out
        if op == "avg":
            ds = by_key.get(("sum", col))
            dc = by_key.get(("count", col))
            if ds is None or dc is None:
                return None
            return BinaryOp("/", FunctionCall("sum", [Column(ds)]),
                            FunctionCall("sum", [Column(dc)]))
        return None

    for call in a.agg_calls:
        if call.distinct or call.params:
            return None
        if call.arg is None:
            col = None
        elif isinstance(call.arg, Column):
            col = call.arg.name
        else:
            return None
        if map_call(call.op, col) is None:
            return None
        if call.op in ("first", "last") and qtags != tag_set:
            # collapsing the flow's tag dimension loses intra-bucket
            # timestamps: first/last over per-series sink rows cannot
            # reproduce the globally time-ordered raw answer
            return None

    # ---- build the rewritten statement ----
    new_q = copy.deepcopy(query)
    new_q.from_ = ast.TableRef(
        name=ObjectName([flow.catalog, flow.schema, flow.sink]),
        alias=query.from_.alias)

    def xform(e):
        if e is None or isinstance(e, (ast.Literal, ast.Star)):
            return e
        if isinstance(e, Column):
            return Column(e.name)        # drop source-table qualifiers
        if isinstance(e, FunctionCall) and e.over is None and \
                not e.distinct:
            op = _AGG_CANON.get(e.name, e.name)
            if op == "avg" or op == "count" or op in _DIRECT_OPS:
                col = None
                shape_ok = False
                if op == "count" and (not e.args or
                                      isinstance(e.args[0], ast.Star)):
                    shape_ok = True            # count(*)
                elif len(e.args) == 1 and isinstance(e.args[0], Column):
                    col = e.args[0].name
                    shape_ok = True
                if shape_ok:
                    repl = map_call(op, col)
                    if repl is not None:
                        return repl
        if isinstance(e, FunctionCall):
            out = FunctionCall(e.name, [xform(x) for x in e.args],
                               e.distinct)
            if e.over is not None:
                out.over = ast.WindowSpec(
                    [xform(x) for x in e.over.partition_by],
                    [(xform(x), asc) for x, asc in e.over.order_by],
                    e.over.frame)
            return out
        from ..query.planner import map_expr_children
        return map_expr_children(e, xform)

    new_q.projections = []
    for item in query.projections:
        alias = item.alias or expr_name(item.expr)
        new_q.projections.append(ast.SelectItem(xform(item.expr), alias))
    new_q.where = xform(query.where)
    new_q.group_by = [xform(g) for g in query.group_by]
    new_q.having = xform(query.having)
    new_q.order_by = [(xform(e), asc) for e, asc in query.order_by]

    note = (f"flow {flow.name}: {flow.source} -> {flow.sink}, "
            f"stride {s}ms -> {bucket.stride_ms}ms")
    return RollupRewrite(flow=flow, query=new_q, sink=flow.sink, note=note)
