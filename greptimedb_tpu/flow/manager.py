"""FlowManager: lifecycle + incremental maintenance of continuous rollups.

Reference behavior: GreptimeDB's flow engine (`CREATE FLOW ... AS SELECT
<aggs> FROM src GROUP BY date_bin(...)`) maintains a materialized rollup
table as new rows arrive. Here the fold is the TPU sorted-segment reduce
(storage/downsample.py) driven incrementally:

- each flow tracks a per-source-region **watermark** — the committed
  sequence it last folded. A fold selects only rows beyond the watermark
  (read off the merged-scan cache's per-row sequence column), finds the
  earliest time bucket those rows touch, and re-reduces the source from
  that bucket boundary forward. Because the sink rows carry the same
  (tags, bucket_ts) key, re-folding a partially-filled top-of-bucket is
  idempotent: MVCC dedup in the sink region keeps the newest fold.
- specs + watermarks persist across restarts: standalone in a JSON doc
  next to the mito manifests on the object store, distributed in the
  meta kv — the same split the catalog uses.
- the background task is **cooperative under tests**: `tick()` folds all
  flows once; `start_background()` wraps it in a RepeatedTask only when
  the host opts in (DatanodeInstance skips it under pytest so no
  free-running threads race the test harness).
"""

from __future__ import annotations

import json
import logging
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..common import failpoint as _fp
from ..common.time import TimeUnit
from ..errors import (InvalidArgumentsError, PlanError, TableNotFoundError,
                      UnsupportedError)
from ..sql import ast

logger = logging.getLogger(__name__)

_fp.register("flow_fold")
_fp.register("flow_fold_commit")

#: aggregate ops a flow can materialize. avg is exact here because every
#: fold recomputes whole buckets from source rows (sum + count moments,
#: finalized at write time — the same decomposition the plan IR applies);
#: note a stored avg column only serves queries at the flow's own tag
#: grain — the read-time rollup rewrite (flow/rewrite.py) still derives
#: coarser-grouped avg from a stored sum + count pair, never by
#: averaging averages.
FLOW_OPS = ("sum", "count", "avg", "min", "max", "first", "last")


@dataclass
class FlowAgg:
    op: str                        # sum/count/avg/min/max/first/last
    column: Optional[str]          # source field; None = count(*)
    dest: str                      # sink column name

    def to_dict(self) -> dict:
        return {"op": self.op, "column": self.column, "dest": self.dest}

    @staticmethod
    def from_dict(d: dict) -> "FlowAgg":
        return FlowAgg(d["op"], d.get("column"), d["dest"])

    def describe(self) -> str:
        return f"{self.op}({self.column or '*'}) -> {self.dest}"


@dataclass
class FlowSpec:
    name: str
    catalog: str
    schema: str                    # database name
    source: str                    # source table
    sink: str                      # rollup table
    stride_ms: int
    origin_ms: int
    ts_column: str
    tags: List[str]
    aggs: List[FlowAgg]
    raw_sql: str = ""
    #: per-source-region watermark: region name -> {"seq": int, "ts": int}
    watermarks: Dict[str, dict] = field(default_factory=dict)
    #: fold counters: folds / rows_folded / buckets_written
    stats: Dict[str, int] = field(default_factory=dict)

    @property
    def key(self) -> str:
        return f"{self.catalog}.{self.schema}.{self.name}"

    def watermark_ts(self) -> Optional[int]:
        vals = [w.get("ts") for w in self.watermarks.values()
                if w.get("ts") is not None]
        return max(vals) if vals else None

    def to_dict(self) -> dict:
        return {
            "name": self.name, "catalog": self.catalog,
            "schema": self.schema, "source": self.source, "sink": self.sink,
            "stride_ms": self.stride_ms, "origin_ms": self.origin_ms,
            "ts_column": self.ts_column, "tags": list(self.tags),
            "aggs": [a.to_dict() for a in self.aggs],
            "raw_sql": self.raw_sql, "watermarks": self.watermarks,
            "stats": self.stats,
        }

    @staticmethod
    def from_dict(d: dict) -> "FlowSpec":
        return FlowSpec(
            name=d["name"], catalog=d["catalog"], schema=d["schema"],
            source=d["source"], sink=d["sink"],
            stride_ms=int(d["stride_ms"]),
            origin_ms=int(d.get("origin_ms", 0)),
            ts_column=d["ts_column"], tags=list(d["tags"]),
            aggs=[FlowAgg.from_dict(a) for a in d["aggs"]],
            raw_sql=d.get("raw_sql", ""),
            watermarks=dict(d.get("watermarks", {})),
            stats=dict(d.get("stats", {})))


# ---------------------------------------------------------------------------
# spec compilation (CREATE FLOW -> FlowSpec)
# ---------------------------------------------------------------------------

def compile_flow(stmt: ast.CreateFlow, src_table, catalog: str,
                 schema_name: str) -> FlowSpec:
    """Validate the flow SELECT against the source table and produce the
    FlowSpec. Raises on anything the incremental fold cannot maintain."""
    from ..query.expr import expr_name
    from ..query.planner import _AGG_CANON
    from ..query.tpu_exec import _match_bucket

    q = stmt.query
    if q.joins or q.where is not None or q.having is not None or \
            q.order_by or q.limit is not None or q.offset or q.distinct:
        raise PlanError(
            "CREATE FLOW supports plain single-table aggregates: no "
            "JOIN/WHERE/HAVING/ORDER BY/LIMIT/DISTINCT")
    if q.from_ is None or q.from_.name is None:
        raise PlanError("CREATE FLOW needs a FROM table")
    if not q.group_by:
        raise PlanError("CREATE FLOW needs GROUP BY date_bin(stride, ts)")

    src_schema = src_table.schema
    tc = src_schema.timestamp_column
    if tc is None:
        raise PlanError("flow source table has no time index")
    if tc.dtype.time_unit != TimeUnit.MILLISECOND:
        raise UnsupportedError(
            "flows require a millisecond time index (date_bin strides "
            "are millisecond-based)")
    tag_names = src_schema.tag_names()
    field_names = set(src_schema.field_names())

    rule = getattr(src_table, "partition_rule", None)
    if rule is not None and tc.name in rule.partition_columns():
        raise UnsupportedError(
            "flow source must not be partitioned on the time index: a "
            "series' bucket could span regions and partial folds would "
            "clobber each other")

    # resolve GROUP BY aliases / ordinals against the projection list
    # (the same rule planner.analyze applies)
    alias_map = {item.alias.lower(): item.expr
                 for item in q.projections if item.alias}

    def resolve_ref(g):
        if isinstance(g, ast.Literal) and isinstance(g.value, int):
            idx = g.value - 1
            if 0 <= idx < len(q.projections):
                return q.projections[idx].expr
            raise PlanError(f"GROUP BY ordinal {g.value} out of range")
        if isinstance(g, ast.Column) and g.table is None and \
                g.name.lower() in alias_map:
            return alias_map[g.name.lower()]
        return g

    bucket = None
    tags: List[str] = []
    group_keys: Dict[str, str] = {}      # expr_name -> kind
    for g in [resolve_ref(x) for x in q.group_by]:
        if isinstance(g, ast.Column) and g.name in tag_names:
            tags.append(g.name)
            group_keys[expr_name(g)] = "tag"
            continue
        b = _match_bucket(g, tc.name)
        if b is not None and bucket is None:
            bucket = b
            group_keys[expr_name(g)] = "bucket"
            continue
        raise PlanError(
            f"flow GROUP BY must be tag columns plus exactly one "
            f"date_bin/date_trunc over {tc.name!r}; got {expr_name(g)!r}")
    if bucket is None:
        raise PlanError(
            "CREATE FLOW needs a date_bin/date_trunc time bucket in "
            "GROUP BY (bad or missing stride)")
    if bucket.stride_ms <= 0:
        raise PlanError(f"bad flow stride {bucket.stride_ms}ms")

    aggs: List[FlowAgg] = []
    used_names = set(tag_names) | {tc.name}
    for item in q.projections:
        e = item.expr
        if isinstance(e, ast.Star):
            raise PlanError("'*' projection is not valid in CREATE FLOW")
        if expr_name(e) in group_keys:
            continue                     # group key passthrough
        if not isinstance(e, ast.FunctionCall):
            raise PlanError(
                f"flow projections must be group keys or aggregates; "
                f"got {expr_name(e)!r}")
        op = _AGG_CANON.get(e.name, e.name)
        if op in ("approx_distinct", "approx_percentile", "median"):
            raise UnsupportedError(
                f"{op} partials are sketches, not columns a flow sink "
                f"can store; query the raw table — the distributed "
                f"sketch pushdown (README 'Distributed aggregation') "
                f"serves it without materialization")
        if op not in FLOW_OPS:
            raise UnsupportedError(
                f"aggregate {e.name!r} is not derivable in a flow "
                f"(supported: {', '.join(FLOW_OPS)})")
        if e.distinct:
            raise UnsupportedError("DISTINCT aggregates in flows")
        col: Optional[str] = None
        if e.args and isinstance(e.args[0], ast.Star):
            if op != "count":
                raise PlanError(f"{op}(*) is not valid")
        elif e.args:
            if not isinstance(e.args[0], ast.Column) or len(e.args) > 1:
                raise PlanError(
                    f"flow aggregates take a plain column argument; got "
                    f"{expr_name(e)!r}")
            col = e.args[0].name
            if col not in field_names:
                raise PlanError(
                    f"column {col!r} is not a field of the source table")
            cs = src_schema.column_schema(col)
            if cs.dtype.is_string or cs.dtype.is_binary:
                if op != "count":
                    raise PlanError(
                        f"{op} over non-numeric column {col!r}")
        elif op != "count":
            raise PlanError(f"{op}() needs an argument")
        dest = item.alias or (f"{col}_{op}" if col else "row_count")
        if dest in used_names:
            raise PlanError(f"duplicate flow output column {dest!r}")
        used_names.add(dest)
        aggs.append(FlowAgg(op, col, dest))
    if not aggs:
        raise PlanError("CREATE FLOW needs at least one aggregate")
    if set(tags) != set(tag_names):
        # the fold reduces per (series, bucket); a sink keyed by a tag
        # SUBSET would collapse distinct series onto one row key and
        # MVCC dedup would silently drop all but one. Queries that want
        # coarser grouping still get it — the rollup rewrite collapses
        # tags at read time.
        missing = sorted(set(tag_names) - set(tags))
        raise PlanError(
            f"flow GROUP BY must include every tag column of the source "
            f"(missing: {', '.join(missing)}); group coarser at query "
            f"time instead")

    return FlowSpec(
        name=stmt.name, catalog=catalog, schema=schema_name,
        source=q.from_.name.table, sink=stmt.sink or stmt.name,
        stride_ms=bucket.stride_ms, origin_ms=bucket.origin,
        ts_column=tc.name, tags=tags, aggs=aggs, raw_sql=stmt.raw_sql)


def sink_schema_for(spec: FlowSpec, src_schema):
    """(Schema, pk_indices) for the rollup sink table."""
    from ..datatypes import data_type as dt
    from ..datatypes.schema import ColumnSchema, Schema, SemanticType
    cols = []
    for tag in spec.tags:
        cs = src_schema.column_schema(tag)
        cols.append(ColumnSchema(tag, cs.dtype, nullable=False,
                                 semantic_type=SemanticType.TAG))
    ts = src_schema.column_schema(spec.ts_column)
    cols.append(ColumnSchema(spec.ts_column, ts.dtype, nullable=False,
                             semantic_type=SemanticType.TIMESTAMP))
    for a in spec.aggs:
        cols.append(ColumnSchema(a.dest, dt.FLOAT64, nullable=True))
    schema = Schema(cols)
    pk = [i for i, c in enumerate(cols)
          if c.semantic_type == SemanticType.TAG]
    return schema, pk


def _validate_sink(spec: FlowSpec, sink_table) -> None:
    schema = sink_table.schema
    tc = schema.timestamp_column
    if tc is None or tc.name != spec.ts_column:
        raise InvalidArgumentsError(
            f"sink table {spec.sink!r} time index must be "
            f"{spec.ts_column!r}")
    have_tags = set(schema.tag_names())
    missing = [t for t in spec.tags if t not in have_tags]
    if missing:
        raise InvalidArgumentsError(
            f"sink table {spec.sink!r} is missing tag column(s) {missing}")
    for a in spec.aggs:
        if not schema.contains(a.dest):
            raise InvalidArgumentsError(
                f"sink table {spec.sink!r} is missing column {a.dest!r}")


# ---------------------------------------------------------------------------
# durable state stores
# ---------------------------------------------------------------------------

FLOW_DOC_PREFIX = "flow/"


class ObjectStoreFlowStore:
    """Standalone persistence: one JSON doc per flow on the object store,
    next to the mito manifests (the same durability story the catalog
    uses)."""

    def __init__(self, store, state_prefix: str = ""):
        self.store = store
        self.prefix = f"{state_prefix}{FLOW_DOC_PREFIX}"

    def _key(self, flow_key: str) -> str:
        return f"{self.prefix}{flow_key}.json"

    def load_all(self) -> List[dict]:
        docs = []
        for key in self.store.list(self.prefix):
            if not key.endswith(".json"):
                continue
            try:
                docs.append(json.loads(self.store.read(key)))
            except Exception:  # noqa: BLE001 — a corrupt doc skips one flow
                logger.exception("flow store: cannot read %s", key)
        return docs

    def save(self, spec: FlowSpec) -> None:
        self.store.write(self._key(spec.key),
                         json.dumps(spec.to_dict()).encode())

    def delete(self, flow_key: str) -> None:
        self.store.delete(self._key(flow_key))


class KvFlowStore:
    """Distributed persistence: flow docs in the meta kv (reference: the
    flownode registers flows through meta). Accepts a raw kv
    (put/range/delete) or a MetaClient (kv_put/kv_range/kv_delete)."""

    KV_PREFIX = "__flow/"

    def __init__(self, kv):
        self._put = getattr(kv, "kv_put", None) or kv.put
        self._range = getattr(kv, "kv_range", None) or kv.range
        self._del = getattr(kv, "kv_delete", None) or kv.delete

    def load_all(self) -> List[dict]:
        docs = []
        for key, v in self._range(self.KV_PREFIX):
            try:
                docs.append(json.loads(v))
            except Exception:  # noqa: BLE001 — one corrupt doc must not
                logger.exception(       # keep the frontend from starting
                    "flow store: cannot decode %s", key)
        return docs

    def save(self, spec: FlowSpec) -> None:
        self._put(f"{self.KV_PREFIX}{spec.key}",
                  json.dumps(spec.to_dict()).encode())

    def delete(self, flow_key: str) -> None:
        self._del(f"{self.KV_PREFIX}{flow_key}")


# ---------------------------------------------------------------------------
# manager
# ---------------------------------------------------------------------------

class FlowManager:
    """Owns every flow's spec, watermark and fold loop for one node."""

    def __init__(self, catalog_manager, state_store,
                 create_sink_fn: Optional[Callable] = None):
        self.catalog = catalog_manager
        self.store = state_store
        #: create_sink_fn(spec, schema, pk_indices) -> Table; when None the
        #: sink table must already exist
        self.create_sink_fn = create_sink_fn
        from ..common.locks import TrackedLock, TrackedRLock
        from ..common.tracking import tracked_state
        self._lock = TrackedRLock("flow.manager")
        #: serializes folds: the background tick thread and a query-path
        #: refresh() must not fold the same flow concurrently (both would
        #: read one watermark and double-count the same delta, and
        #: store.save would serialize a mid-mutation watermark dict)
        self._fold_lock = TrackedLock("flow.fold")
        self._flows: Dict[str, FlowSpec] = tracked_state(
            {}, "flow.manager.flows")
        self._task = None
        #: read-path refresh floor for sources WITHOUT sequence counters
        #: (DistTables): lagging() cannot cheaply answer there, so
        #: refresh() folds at most once per this interval instead of on
        #: every rollup-served query
        self.generic_refresh_min_interval_s = 5.0
        self._last_generic_fold: Dict[str, float] = {}

    # ---- lifecycle ----
    def recover(self) -> None:
        """Reload persisted flows (watermarks included) after restart."""
        if self.store is None:
            return
        for doc in self.store.load_all():
            try:
                spec = FlowSpec.from_dict(doc)
            except Exception:  # noqa: BLE001
                logger.exception("flow recover: bad doc %r", doc)
                continue
            with self._lock:
                self._flows[spec.key] = spec
        if self._flows:
            logger.info("recovered %d flow(s): %s", len(self._flows),
                        ", ".join(sorted(self._flows)))

    def start_background(self, interval_s: float = 10.0) -> None:
        """Free-running tick loop — hosts opt in explicitly; tests drive
        `tick()` cooperatively instead (tier-1 safety)."""
        if self._task is not None:
            return
        from ..storage.scheduler import RepeatedTask
        self._task = RepeatedTask(interval_s, self.tick, name="flow-tick")
        self._task.start()

    def stop(self) -> None:
        if self._task is not None:
            self._task.stop()
            self._task = None

    # ---- DDL ----
    def create_flow(self, stmt: ast.CreateFlow, ctx) -> FlowSpec:
        catalog, schema_name = ctx.current_catalog, ctx.current_schema
        if stmt.query is None or stmt.query.from_ is None or \
                stmt.query.from_.name is None:
            raise PlanError("CREATE FLOW needs a FROM table")
        src_cat, src_schema, _ = ctx.resolve(stmt.query.from_.name)
        if (src_cat, src_schema) != (catalog, schema_name):
            # the flow is keyed (and later SHOWn/DROPped) under the
            # session schema — a cross-schema source would make it
            # unmanageable from where it was created
            raise UnsupportedError(
                f"flow source must live in the current database "
                f"({schema_name}); USE {src_schema} first")
        key = f"{catalog}.{schema_name}.{stmt.name}"
        with self._lock:
            if key in self._flows:
                if stmt.if_not_exists:
                    return self._flows[key]
                raise InvalidArgumentsError(
                    f"flow {stmt.name!r} already exists")
        src = self.catalog.table(catalog, schema_name,
                                 stmt.query.from_.name.table)
        if src is None:
            raise TableNotFoundError(
                f"flow source table "
                f"{stmt.query.from_.name.table!r} not found")
        spec = compile_flow(stmt, src, catalog, schema_name)
        if spec.sink == spec.source:
            raise InvalidArgumentsError(
                "flow sink must differ from its source table")
        sink = self.catalog.table(catalog, schema_name, spec.sink)
        if sink is None:
            if self.create_sink_fn is None:
                raise TableNotFoundError(
                    f"flow sink table {spec.sink!r} not found (create it "
                    f"first)")
            schema, pk = sink_schema_for(spec, src.schema)
            sink = self.create_sink_fn(spec, schema, pk)
        _validate_sink(spec, sink)
        with self._lock:
            # re-check: a concurrent CREATE FLOW may have registered the
            # name while this one compiled / created the sink
            if key in self._flows:
                if stmt.if_not_exists:
                    return self._flows[key]
                raise InvalidArgumentsError(
                    f"flow {stmt.name!r} already exists")
            self._flows[key] = spec
            if self.store is not None:
                self.store.save(spec)
        from ..common.telemetry import increment_counter
        increment_counter("flow_create")
        logger.info("created flow %s: %s -> %s stride=%dms aggs=[%s]",
                    spec.name, spec.source, spec.sink, spec.stride_ms,
                    ", ".join(a.describe() for a in spec.aggs))
        return spec

    def drop_flow(self, name: str, ctx, if_exists: bool = False) -> bool:
        key = f"{ctx.current_catalog}.{ctx.current_schema}.{name}"
        with self._lock:
            spec = self._flows.pop(key, None)
            if spec is None:
                if if_exists:
                    return False
                raise InvalidArgumentsError(f"flow {name!r} not found")
            if self.store is not None:
                self.store.delete(key)
        return True

    # ---- introspection ----
    def flows(self, catalog: Optional[str] = None,
              schema: Optional[str] = None) -> List[FlowSpec]:
        with self._lock:
            out = list(self._flows.values())
        if catalog is not None:
            out = [f for f in out if f.catalog == catalog]
        if schema is not None:
            out = [f for f in out if f.schema == schema]
        return sorted(out, key=lambda f: f.key)

    def flows_for_source(self, catalog: str, schema: str,
                         table_name: str) -> List[FlowSpec]:
        return [f for f in self.flows(catalog, schema)
                if f.source == table_name]

    def get(self, catalog: str, schema: str, name: str
            ) -> Optional[FlowSpec]:
        with self._lock:
            return self._flows.get(f"{catalog}.{schema}.{name}")

    # ---- maintenance ----
    def tick(self) -> Dict[str, int]:
        """Fold every flow once; returns flow key -> bucket rows written.
        Errors are contained per flow (background-loop safety). Each fold
        is a background job with its own root trace — the read-path
        refresh() folds stay on the querying statement's trace instead."""
        from ..common import background_jobs
        out: Dict[str, int] = {}
        for spec in self.flows():
            try:
                with background_jobs.job("flow_fold", table=spec.sink,
                                         flow=spec.name):
                    out[spec.key] = self.fold_flow(spec)
            except Exception:  # noqa: BLE001
                logger.exception("flow %s fold failed", spec.key)
        return out

    def _source_counters(self, spec: FlowSpec):
        """The source's storage regions when sequence counters exist
        locally, else None (DistTables / non-region tables)."""
        from . import lowering
        src = self.catalog.table(spec.catalog, spec.schema, spec.source)
        if src is None:
            return src, None
        return src, lowering.source_counters(src)

    def lagging(self, spec: FlowSpec) -> bool:
        """Cheap freshness probe: does the source hold committed rows the
        flow has not folded? Reads only sequence counters — no scan."""
        from . import lowering
        src, regions = self._source_counters(spec)
        if src is None:
            return False
        if regions is None:
            return True                  # no counters: assume lagging
        return lowering.source_lagging(spec, regions)

    def refresh(self, spec: FlowSpec) -> int:
        """Fold only if the source advanced past the watermark (the
        read-side hook: a rollup-rewritten query first catches the sink
        up, so rewrite answers equal the raw scan). Counter-less sources
        cannot answer "did anything change?" cheaply, so their read-path
        folds are rate-limited instead of running per query."""
        src, regions = self._source_counters(spec)
        if src is None:
            return 0
        if regions is None:
            import time
            now = time.monotonic()
            last = self._last_generic_fold.get(spec.key)
            if last is not None and \
                    now - last < self.generic_refresh_min_interval_s:
                return 0
            self._last_generic_fold[spec.key] = now
            return self.fold_flow(spec)
        if not self.lagging(spec):
            return 0
        return self.fold_flow(spec)

    def fold_flow(self, spec: FlowSpec) -> int:
        """One incremental fold of a flow. Returns bucket rows written."""
        from ..common import exec_stats
        from ..common.telemetry import increment_counter, span, timer
        src = self.catalog.table(spec.catalog, spec.schema, spec.source)
        dst = self.catalog.table(spec.catalog, spec.schema, spec.sink)
        if src is None or dst is None:
            logger.warning("flow %s: source or sink missing; skipping",
                           spec.key)
            return 0
        with self._fold_lock:
            _fp.fail_point("flow_fold")
            wm_before = json.dumps(spec.watermarks, sort_keys=True)
            with span("flow_fold", flow=spec.name, source=spec.source,
                      sink=spec.sink), timer("flow_fold"):
                # all data access (regions, scan cache, scan_batches,
                # IR plans) lives in flow/lowering.py — the one module
                # under flow/ sanctioned (greptlint GL14) to touch it
                from . import lowering
                written, new_rows = lowering.fold_source(spec, src, dst)
            if written or new_rows:
                spec.stats["folds"] = spec.stats.get("folds", 0) + 1
                spec.stats["rows_folded"] = \
                    spec.stats.get("rows_folded", 0) + new_rows
                spec.stats["buckets_written"] = \
                    spec.stats.get("buckets_written", 0) + written
                increment_counter("flow_folds")
                increment_counter("flow_rows_folded", new_rows)
                increment_counter("flow_buckets_written", written)
                exec_stats.record("flow_fold", rows=new_rows,
                                  flow=spec.name, buckets=written)
            # persist only when the fold changed something — an idle
            # background tick must not PUT a byte-identical doc per flow
            dirty = bool(written or new_rows) or \
                json.dumps(spec.watermarks, sort_keys=True) != wm_before
            # crash HERE = sink rows written, watermark never persisted:
            # the reopened flow re-folds the same window, and sink MVCC
            # overwrite keeps the re-fold idempotent (no double counting)
            _fp.fail_point("flow_fold_commit")
            with self._lock:
                if dirty and self.store is not None and \
                        spec.key in self._flows:
                    self.store.save(spec)
        return written

