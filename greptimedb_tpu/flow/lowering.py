"""Flow folds lowered onto the columnar plan IR (query/ir.py).

Reference behavior: GreptimeDB's flow engine plans its continuous
aggregates through the same query engine as ad-hoc SQL. Here a
FlowSpec's aggregates compile into the same `TpuPlan` SQL and PromQL
lower into, so folds ride every fast path the IR executor owns:

- **region-backed sources** fold through the device sorted-segment
  reducer (storage/downsample.py) with sequence watermarks — the
  device rollup path;
- **distributed sources** (DistTables) ship the TpuPlan through
  `execute_tpu_plan`: datanodes reduce their regions and the frontend
  folds *moment frames*, never raw samples, riding cost-based scatter
  and per-SST pruning. Shapes the scatter declines (cost-based
  raw-pull, version-skewed datanodes) degrade to a raw scan + host
  reduce — slower, never wrong.

This module is the ONE place under flow/ sanctioned (greptlint GL14)
to touch storage regions, the device scan cache or raw scan_batches;
FlowManager (manager.py) owns lifecycle/watermark policy and delegates
every data access here.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional, Tuple

import numpy as np

from ..common.time import TimestampRange

logger = logging.getLogger(__name__)

#: the bucket expression key flow plans use (any stable name works; it
#: only namespaces the finalized frame's bucket column)
FLOW_BUCKET_KEY = "__flow_bucket"


def set_wm(spec, key: str, val: dict) -> None:
    """Atomic watermark update: readers (SHOW FLOWS, metrics) iterate
    spec.watermarks without the fold lock, so mutate by swapping in a
    fresh dict instead of inserting into the live one."""
    spec.watermarks = {**spec.watermarks, key: val}


def source_counters(src):
    """The source's storage regions when sequence counters exist
    locally, else None (DistTables / non-region tables)."""
    if src is None:
        return None
    regions = getattr(src, "regions", None)
    if not regions or any(
            getattr(r, "version_control", None) is None
            for r in regions.values()):
        return None
    return regions


def source_lagging(spec, regions) -> bool:
    """Sequence-counter freshness probe over a region-backed source."""
    for region in regions.values():
        wm = spec.watermarks.get(region.name, {})
        if region.version_control.committed_sequence > \
                wm.get("seq", -1):
            return True
    return False


def fold_source(spec, src, dst) -> Tuple[int, int]:
    """Route one fold to the right executor: local region-backed
    sources take the sequence-watermarked device fold; everything else
    (DistTables) lowers onto the IR. Returns (buckets written,
    source rows folded)."""
    regions = getattr(src, "regions", None)
    local = bool(regions) and all(
        hasattr(r, "snapshot") and hasattr(r, "series_dict")
        for r in regions.values())
    if local:
        return fold_local(spec, src, dst)
    return fold_generic(spec, src, dst)


# ---------------------------------------------------------------------------
# local region-backed fold (device rollup)
# ---------------------------------------------------------------------------

def fold_local(spec, src, dst) -> Tuple[int, int]:
    """Region-backed source: sequence-watermarked incremental fold via
    the device sorted-segment reducer. Regions past the streaming
    threshold never enter the scan cache — they take a window-bounded
    host fold instead (fold_region_cold), the same residency rule the
    query path applies."""
    from ..query.tpu_exec import SCAN_CACHE, region_streams_cold
    from ..storage.downsample import downsample_region
    agg_specs = [(a.dest, a.op, a.column) for a in spec.aggs]
    written = new_total = 0
    for region in src.regions.values():
        snap = region.snapshot()
        visible = snap.visible_sequence
        wm = spec.watermarks.get(region.name, {})
        wm_seq = wm.get("seq", -1)
        if visible <= wm_seq:
            continue                   # nothing committed since last fold
        if region_streams_cold(region):
            w, n = fold_region_cold(spec, region, snap, dst, wm)
            written += w
            new_total += n
            continue
        scan = SCAN_CACHE.get(region)
        if scan.num_rows == 0:
            if wm.get("rows"):
                # everything this region ever folded was deleted:
                # drop its sink rows (ghost buckets would diverge
                # from the raw scan)
                retract_stale_sink_rows(spec, region, dst, scan)
            set_wm(spec, region.name, {
                "seq": int(visible), "ts": wm.get("ts"), "rows": 0})
            continue
        retracted = False
        if scan.seq is not None and wm_seq >= 0:
            new = scan.seq > wm_seq
            n_new = int(new.sum())
            # retraction probe: the count of still-live rows at or
            # below the watermark must match what the last fold saw —
            # a shrink means a DELETE (or in-place overwrite) removed
            # already-folded rows, possibly in buckets older than any
            # new row (tombstones vanish in the merged scan, so the
            # seq filter alone cannot see them)
            expected_old = wm.get("rows")
            retracted = expected_old is not None and \
                scan.num_rows - n_new != expected_old
            if n_new == 0 and not retracted:
                set_wm(spec, region.name, {
                    "seq": int(visible), "ts": wm.get("ts"),
                    "rows": int(scan.num_rows)})
                continue
            if n_new:
                ts_max = int(scan.ts[new].max())
            else:
                ts_max = wm.get("ts")
            if retracted:
                # re-fold the whole region so retracted buckets
                # correct themselves; fully-emptied buckets are
                # deleted from the sink below
                from ..common.telemetry import increment_counter
                increment_counter("flow_retraction_refolds")
                rng = None
            else:
                ts_min = int(scan.ts[new].min())
                # re-fold from the boundary of the earliest touched
                # bucket: a partially-folded top-of-bucket is
                # overwritten in place
                lo = ((ts_min - spec.origin_ms) // spec.stride_ms) \
                    * spec.stride_ms + spec.origin_ms
                rng = TimestampRange(lo, None)
        else:
            # first fold (or no sequence column): fold everything
            n_new = scan.num_rows
            ts_max = int(scan.ts.max())
            rng = None
        written += downsample_region(
            region, dst, stride_ms=spec.stride_ms,
            aggs=agg_specs, time_range=rng,
            origin_ms=spec.origin_ms)
        if retracted:
            retract_stale_sink_rows(spec, region, dst, scan)
        prev_ts = wm.get("ts")
        if ts_max is None:
            ts_max = prev_ts
        set_wm(spec, region.name, {
            "seq": int(visible),
            "ts": max(ts_max, prev_ts)
            if prev_ts is not None and ts_max is not None else ts_max,
            "rows": int(scan.num_rows)})
        new_total += n_new
    return written, new_total


def retract_stale_sink_rows(spec, region, dst, scan) -> None:
    """Full-bucket DELETE retraction: remove sink rows owned by this
    region's series whose bucket no longer holds any live source row
    — a refold alone cannot emit them, so ghost buckets would make
    rollup answers diverge from the raw scan. The sink is rollup-
    sized (stride× smaller), so the scan here is cheap relative to
    the retraction refold that triggered it."""
    sd = region.series_dict
    tag_names = list(sd.tag_names)
    nt = len(tag_names)
    if scan.num_rows:
        buckets = ((scan.ts - spec.origin_ms) // spec.stride_ms) \
            * spec.stride_ms + spec.origin_ms
        live_cols = [sd.decode_tag_column(scan.series_ids, i)
                     for i in range(nt)]
        live = set(zip(*live_cols, buckets.tolist()))
    else:
        live = set()
    # ownership filter: every series this region has ever encoded —
    # a multi-region (tag-partitioned) source must never delete a
    # sibling region's sink rows
    ids = np.arange(sd.num_series, dtype=np.int32)
    own_cols = [sd.decode_tag_column(ids, i) for i in range(nt)]
    owned = set(zip(*own_cols)) if nt else {()}
    need = tag_names + [spec.ts_column]
    to_del: Dict[str, list] = {c: [] for c in need}
    for b in dst.scan_batches(projection=need):
        d = b.to_pydict()
        for vals in zip(*(d[c] for c in need)):
            tags_t = tuple(vals[:nt])
            if tags_t not in owned:
                continue
            if tags_t + (vals[nt],) not in live:
                for c, v in zip(need, vals):
                    to_del[c].append(v)
    n = len(to_del[spec.ts_column])
    if n:
        dst.delete(to_del)
        from ..common.telemetry import increment_counter
        increment_counter("flow_sink_rows_retracted", n)
        logger.info("flow %s: retracted %d emptied bucket row(s) "
                    "from %s", spec.key, n, spec.sink)


def fold_region_cold(spec, region, snap, dst, wm: dict) -> Tuple[int, int]:
    """Host fold of one over-threshold region: a merged read bounded
    to the refold window (the data tail past the ts watermark), never
    touching the scan cache or device memory. Timestamp-watermarked,
    so it shares fold_generic's documented out-of-order limit and
    has no retraction probe ("rows" stays unset)."""
    import pandas as pd
    visible = snap.visible_sequence
    wm_ts = wm.get("ts")
    rng = None
    if wm_ts is not None:
        lo = ((wm_ts - spec.origin_ms) // spec.stride_ms) \
            * spec.stride_ms + spec.origin_ms
        rng = TimestampRange(lo, None)
    need = sorted({a.column for a in spec.aggs
                   if a.column is not None})
    data = snap.read_merged(projection=need, time_range=rng)
    if data.num_rows == 0:
        set_wm(spec, region.name,
               {"seq": int(visible), "ts": wm_ts})
        return 0, 0
    cols = {}
    sd = data.series_dict
    for i, tag in enumerate(sd.tag_names):
        cols[tag] = sd.decode_tag_column(data.series_ids, i)
    cols[spec.ts_column] = data.ts
    for name, (vals, valid) in data.fields.items():
        if valid is None:
            cols[name] = vals
        elif vals.dtype == object:     # count over a string column
            arr = vals.copy()
            arr[~valid] = None
            cols[name] = arr
        else:
            arr = vals.astype(np.float64)
            arr[~valid] = np.nan
            cols[name] = arr
    df = pd.DataFrame(cols)
    out_cols = reduce_frame(spec, df)
    dst.insert(out_cols)
    ts_max = int(data.ts.max())
    set_wm(spec, region.name, {
        "seq": int(visible),
        "ts": max(ts_max, wm_ts) if wm_ts is not None else ts_max})
    n_buckets = len(out_cols[spec.ts_column])
    return n_buckets, int(data.num_rows)


# ---------------------------------------------------------------------------
# generic fold (DistTables): moment frames first, raw rows as fallback
# ---------------------------------------------------------------------------

def fold_plan(spec, schema, lo_ms: Optional[int]):
    """Compile the FlowSpec's aggregates into the IR aggregate node —
    the same TpuPlan SQL and PromQL lower into. A hidden count(*)
    rides along so the fold can report rows folded without a second
    scan."""
    from ..query import ir
    aggs = [("__rows", "count", None)] + \
        [(a.dest, a.op, a.column) for a in spec.aggs]
    return ir.plan_from_specs(
        schema, aggs, group_tags=list(spec.tags),
        bucket=ir.BucketGroup(spec.stride_ms, spec.origin_ms,
                              FLOW_BUCKET_KEY),
        time_lo=lo_ms)


def _ir_fold(spec, src, dst, lo_ms: Optional[int]
             ) -> Tuple[int, int, Optional[int]]:
    """One IR fold: datanodes reduce, the frontend folds moment frames
    and writes finalized buckets to the sink. Raises UnsupportedError
    when the plan should degrade to the raw path."""
    from ..query import ir
    from ..query.planner import _group_slot
    plan = fold_plan(spec, src.schema, lo_ms)
    df = ir.execute_agg_plan(src, plan)
    rows = df["__rows"].to_numpy() if "__rows" in df else np.array([])
    df = df[rows > 0] if len(df) else df
    if not len(df):
        return 0, 0, None
    cols: Dict[str, object] = {
        t: df[_group_slot(t)].tolist() for t in spec.tags}
    buckets = df[_group_slot(FLOW_BUCKET_KEY)].astype(np.int64).to_numpy()
    cols[spec.ts_column] = buckets
    for a in spec.aggs:
        vals = df[a.dest].astype(np.float64)
        nan = vals.isna()
        cols[a.dest] = [None if m else float(v)
                        for v, m in zip(vals, nan)] \
            if nan.any() else vals.to_numpy()
    dst.insert(cols)
    n_new = int(df["__rows"].sum())
    # the watermark only ever rounds DOWN to its bucket boundary, so
    # the max bucket start is as good as the max raw timestamp
    return len(buckets), n_new, int(buckets.max())


def fold_generic(spec, src, dst) -> Tuple[int, int]:
    """Fold a source without local storage regions (distributed
    frontends). Lowerable specs ride the IR: the plan scatters through
    `execute_tpu_plan` and only moment frames cross the wire. When the
    scatter declines (cost-based raw-pull, version-skewed datanode,
    `SET dist_partial_agg = 0`) the fold degrades to scan_batches over
    the refold window + a host reduce — same answer, more bytes.

    Known limit of the ts watermark: with no per-row sequence to
    consult, a row arriving LATER than the watermark bucket (out of
    order by more than one stride) is not re-folded — the sink keeps
    the earlier fold for that bucket until a wider refold. The local
    region path does not have this gap (its watermark is the
    committed sequence)."""
    import pandas as pd

    from ..errors import UnsupportedError
    wm = spec.watermarks.get("__table__", {})
    wm_ts = wm.get("ts")
    lo = None
    if wm_ts is not None:
        lo = ((wm_ts - spec.origin_ms) // spec.stride_ms) \
            * spec.stride_ms + spec.origin_ms
    if hasattr(src, "execute_tpu_plan"):
        try:
            written, n_new, ts_max = _ir_fold(spec, src, dst, lo)
            if ts_max is None:
                return 0, 0
            prev = wm.get("ts")
            set_wm(spec, "__table__", {
                "seq": -1, "ts": max(ts_max, prev)
                if prev is not None else ts_max})
            return written, n_new
        except UnsupportedError as e:
            from ..common.telemetry import increment_counter
            increment_counter("flow_ir_fold_degrades")
            logger.info("flow %s: IR fold degraded to raw scan (%s)",
                        spec.key, e)
    rng = TimestampRange(lo, None) if lo is not None else None
    need = list(spec.tags) + [spec.ts_column] + sorted(
        {a.column for a in spec.aggs if a.column is not None})
    batches = src.scan_batches(projection=need, time_range=rng)
    frames = [pd.DataFrame(b.to_pydict()) for b in batches
              if b.num_rows]
    if not frames:
        return 0, 0
    df = pd.concat(frames, ignore_index=True)
    n_new = len(df)
    cols = reduce_frame(spec, df)
    dst.insert(cols)
    ts_max = int(df[spec.ts_column].max())
    prev = wm.get("ts")
    set_wm(spec, "__table__", {
        "seq": -1, "ts": max(ts_max, prev) if prev is not None
        else ts_max})
    return len(cols[spec.ts_column]), n_new


def reduce_frame(spec, df) -> Dict[str, object]:
    """Host twin of the device fold: bucket + groupby over a frame of
    raw rows, returning the sink column dict (shared by the generic
    and cold-region fold paths)."""
    import pandas as pd
    bucket = ((df[spec.ts_column].astype(np.int64) - spec.origin_ms)
              // spec.stride_ms) * spec.stride_ms + spec.origin_ms
    df = df.assign(__bucket=bucket)
    df = df.sort_values(spec.ts_column, kind="stable")
    keys = list(spec.tags) + ["__bucket"]
    gb = df.groupby(keys, dropna=False, sort=False)
    res = {}
    for a in spec.aggs:
        if a.column is None:
            res[a.dest] = gb.size().astype(np.float64)
            continue
        s = gb[a.column]
        if a.op == "sum":
            r = s.sum(min_count=1)
        elif a.op == "avg":
            r = s.mean()
        elif a.op == "count":
            r = s.count().astype(np.float64)
        elif a.op == "min":
            r = s.min()
        elif a.op == "max":
            r = s.max()
        elif a.op == "first":
            r = s.first()
        else:
            r = s.last()
        res[a.dest] = r
    out = pd.DataFrame(res).reset_index()
    cols: Dict[str, object] = {t: out[t].tolist() for t in spec.tags}
    cols[spec.ts_column] = out["__bucket"].astype(np.int64).to_numpy()
    for a in spec.aggs:
        vals = out[a.dest].astype(np.float64)
        nan = vals.isna()
        cols[a.dest] = [None if m else float(v)
                        for v, m in zip(vals, nan)] \
            if nan.any() else vals.to_numpy()
    return cols
