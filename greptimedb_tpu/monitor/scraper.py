"""SelfMonitor: scrape the node's own telemetry into system tables.

The pipeline (reference: GreptimeDB's export-metrics-to-self design):

1. **Snapshot first, write second.** Each tick snapshots the shared
   Prometheus registry (`telemetry.registry_snapshot`) and the
   per-region heat facts BEFORE performing any write, then writes both
   through the *normal ingest path* (`handle_row_insert`, the same
   auto-create/alter route protocol ingest takes) into
   `greptime_private.node_metrics` and `greptime_private.region_heat`.
2. **Never recurse.** The writes run under
   `telemetry.suppress_metrics()`: counters/timers/spans they would
   bump are no-ops, so the next tick's snapshot does not grow from the
   act of recording the previous one — metric values converge on an
   idle node instead of self-amplifying (regression-tested). The
   region-heat walk also skips `greptime_private` itself.
3. **History is ordinary data.** The system tables are plain mito (or
   distributed) tables: SQL/PromQL query them, flows roll them up,
   compaction applies, and the scraper's own retention sweep
   (`SET self_monitor_retention_ms` / GREPTIME_SELF_MONITOR_RETENTION_MS)
   deletes aged rows through the normal DELETE path.

Region heat feeds ROADMAP item 1 (elastic regions need heat *history*
to drive split/migrate): standalone nodes walk their own regions and
derive per-region ingest rates from consecutive ticks; distributed
frontends read the cluster-wide per-(node, region) heat the meta
service accumulates from heartbeats (`MetaSrv.region_heat`).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

PRIVATE_SCHEMA = "greptime_private"
NODE_METRICS_TABLE = "node_metrics"
REGION_HEAT_TABLE = "region_heat"

#: retention for the self-monitoring tables, milliseconds; 0 disables
#: the sweep. Process-wide (SET self_monitor_retention_ms) like the
#: other observability knobs.
from ..common.runtime import env_int as _env_int

_RETENTION_MS: List[int] = [_env_int("GREPTIME_SELF_MONITOR_RETENTION_MS",
                                     7 * 24 * 3600 * 1000)]


def configure_retention(ms: int) -> None:
    """SET self_monitor_retention_ms — 0 disables the sweep."""
    _RETENTION_MS[0] = max(0, int(ms))


def retention_ms() -> int:
    return _RETENTION_MS[0]


class SelfMonitor:
    """One node's scrape loop: cooperative `tick()` (the test surface)
    plus an opt-in RepeatedTask, the FlowManager pattern."""

    def __init__(self, instance, node_label: str = "standalone",
                 meta=None):
        #: the hosting frontend: handle_row_insert + catalog are the
        #: only surface used, so standalone and distributed wire alike
        self.instance = instance
        self.catalog = instance.catalog
        self.node_label = node_label
        self.meta = meta
        from ..common.locks import TrackedLock
        from ..common.tracking import tracked_state
        self._lock = TrackedLock("monitor.scraper")
        self._task = None
        #: (node, region) -> (rows, monotonic_t) of the previous tick,
        #: for the locally-derived per-region ingest rate
        self._prev_heat: Dict[Tuple[str, str], Tuple[int, float]] = {}
        self.stats: Dict[str, object] = tracked_state({
            "ticks": 0, "metric_rows": 0, "heat_rows": 0,
            "rows_written": 0, "retention_deleted": 0,
            "last_tick_ms": 0.0, "last_error": None,
        }, "monitor.scraper.stats")

    # ---- lifecycle ----
    def start_background(self, interval_s: float = 30.0) -> None:
        if self._task is not None:
            return
        from ..storage.scheduler import RepeatedTask
        self._task = RepeatedTask(interval_s, self.tick,
                                  name="self-monitor")
        self._task.start()

    def stop(self) -> None:
        if self._task is not None:
            self._task.stop()
            self._task = None

    # ---- one scrape ----
    def tick(self) -> int:
        """Scrape + write once; returns rows written. Serialized (the
        background task and a test-driven tick must not interleave) and
        error-contained — a failed scrape logs and shows up in the
        self_monitor view, never breaks the host. Runs as a background
        job (own root trace + background_jobs row), like every other
        scheduler-driven loop."""
        from ..common import background_jobs
        from ..common.telemetry import suppress_metrics
        # suppressed END TO END (not just the writes): the tick's own
        # root span must not bump a histogram either, or idle ticks
        # would never converge — the scraper observes, it is not
        # observed (its background_jobs row still registers)
        with suppress_metrics(), background_jobs.job("self_monitor"):
            return self._tick_inner()

    def _tick_inner(self) -> int:
        from ..common.telemetry import registry_snapshot, suppress_metrics
        with self._lock:
            t0 = time.perf_counter()
            now_ms = int(time.time() * 1000)
            try:
                # snapshot BEFORE writing: this tick's own ingest must
                # not appear in the samples it persists
                samples = registry_snapshot()
                heat = self._heat_rows()
                from ..common import admission
                with suppress_metrics(), admission.exempt():
                    # admission-exempt like the metrics suppression:
                    # shedding the observer during overload would blind
                    # the operator exactly when they need the data
                    written = self._write_metrics(samples, now_ms)
                    written += self._write_heat(heat, now_ms)
                    # traces and profile samples flush BEFORE the sweep
                    # so a tightened trace_retention_ms /
                    # profile_retention_ms applies to just-written rows
                    # on the same tick
                    written += self._flush_traces()
                    written += self._flush_profile()
                    deleted = self._enforce_retention(now_ms)
                self.stats["ticks"] = int(self.stats["ticks"]) + 1
                self.stats["metric_rows"] = \
                    int(self.stats["metric_rows"]) + len(samples)
                self.stats["heat_rows"] = \
                    int(self.stats["heat_rows"]) + len(heat)
                self.stats["rows_written"] = \
                    int(self.stats["rows_written"]) + written
                self.stats["retention_deleted"] = \
                    int(self.stats["retention_deleted"]) + deleted
                self.stats["last_error"] = None
                return written
            except Exception as e:  # noqa: BLE001 — background-loop
                logger.exception("self-monitor tick failed")  # safety
                self.stats["last_error"] = str(e)
                return 0
            finally:
                self.stats["last_tick_ms"] = \
                    (time.perf_counter() - t0) * 1e3

    # ---- writers ----
    def _ctx(self):
        from ..session import QueryContext
        return QueryContext(current_schema=PRIVATE_SCHEMA)

    def _write_metrics(self, samples, now_ms: int) -> int:
        if not samples:
            return 0
        from ..datatypes.data_type import FLOAT64, STRING
        n = len(samples)
        cols = {
            "node": [self.node_label] * n,
            "metric_name": [s[0] for s in samples],
            "labels": [s[1] for s in samples],
            "ts": [now_ms] * n,
            "value": [float(s[2]) for s in samples],
            "kind": [s[3] for s in samples],
        }
        return self.instance.handle_row_insert(
            NODE_METRICS_TABLE, cols,
            tag_columns=("node", "metric_name", "labels"),
            timestamp_column="ts",
            types={"value": FLOAT64, "kind": STRING,
                   "node": STRING, "metric_name": STRING,
                   "labels": STRING},
            ctx=self._ctx())

    def _write_heat(self, heat: List[dict], now_ms: int) -> int:
        if not heat:
            return 0
        from ..datatypes.data_type import FLOAT64, INT64, STRING
        cols = {
            "node": [h["node"] for h in heat],
            "region": [h["region"] for h in heat],
            "ts": [now_ms] * len(heat),
            "rows": [int(h["rows"]) for h in heat],
            "size_bytes": [int(h["size_bytes"]) for h in heat],
            "ingest_rate_rps": [float(h["ingest_rate_rps"])
                                for h in heat],
        }
        return self.instance.handle_row_insert(
            REGION_HEAT_TABLE, cols, tag_columns=("node", "region"),
            timestamp_column="ts",
            types={"node": STRING, "region": STRING, "rows": INT64,
                   "size_bytes": INT64, "ingest_rate_rps": FLOAT64},
            ctx=self._ctx())

    # ---- heat sources ----
    def _heat_rows(self) -> List[dict]:
        """Per-(node, region) heat facts for this tick. Cluster-wide via
        the meta service when this frontend has one (heartbeat-fed, so
        every datanode reports even though only the frontend scrapes);
        local region walk otherwise."""
        meta = self.meta
        if meta is not None and hasattr(meta, "region_heat"):
            try:
                return list(meta.region_heat())
            except Exception:  # noqa: BLE001 — heat over a flaky meta
                logger.exception(       # degrades; metrics still write
                    "self-monitor: meta region_heat unavailable")
                return []
        return self._local_heat_rows()

    def _local_heat_rows(self) -> List[dict]:
        from .. import DEFAULT_CATALOG_NAME
        from ..query.stream_exec import region_stat_entries
        regions = []
        catalog = DEFAULT_CATALOG_NAME
        for schema_name in self.catalog.schema_names(catalog):
            if schema_name in (PRIVATE_SCHEMA, "information_schema"):
                continue             # never scrape the scrape target
            for tname in self.catalog.table_names(catalog, schema_name):
                t = self.catalog.table(catalog, schema_name, tname)
                regions.extend(
                    (getattr(t, "regions", None) or {}).values())
        entries, _, _ = region_stat_entries(regions)
        now = time.monotonic()
        out = []
        fresh: Dict[Tuple[str, str], Tuple[int, float]] = {}
        for e in entries:
            key = (self.node_label, e["region"])
            prev = self._prev_heat.get(key)
            rate = 0.0
            if prev is not None and now > prev[1]:
                rate = max(0.0, (e["rows"] - prev[0]) / (now - prev[1]))
            fresh[key] = (e["rows"], now)
            out.append({"node": self.node_label, "region": e["region"],
                        "rows": e["rows"], "size_bytes": e["size_bytes"],
                        "ingest_rate_rps": round(rate, 3)})
        self._prev_heat = fresh
        return out

    #: per-tick sweep ceiling: the first sweep after days of retention
    #: being off (or after tightening the window) must not materialize
    #: millions of key rows inside the scrape lock — it deletes up to
    #: this many rows per table per tick and catches up tick by tick
    SWEEP_BATCH_ROWS = 50_000

    # ---- trace-store flush (common/trace_store.py) ----
    def _flush_traces(self) -> int:
        """Write retained spans queued by the process-wide trace sink,
        and TTL-evict verdictless buffered traces. The sink's flush runs
        under its own suppress_metrics guard."""
        from ..common import trace_store
        sink = trace_store.sink()
        if sink is None:
            return 0
        sink.evict_expired()
        return sink.flush()

    def _flush_profile(self) -> int:
        """Persist the continuous profiler's aggregated folded stacks
        (common/profiler.py). Writer-less samplers (datanode processes)
        report flush() == 0 and keep buffering until drained over
        Flight; the sampler's flush has its own suppress guard."""
        from ..common import profiler
        s = profiler.sampler()
        if s is None:
            return 0
        return s.flush()

    # ---- retention ----
    def _enforce_retention(self, now_ms: int) -> int:
        """Delete system-table rows older than the retention window —
        the same key-scan + delete path user DELETEs take, so the sweep
        works on both topologies. trace_spans sweeps on its own, shorter
        leash (SET trace_retention_ms, default 3d): traces are bulkier
        than metrics."""
        from ..common import trace_store
        deleted = 0
        keep_ms = retention_ms()
        if keep_ms > 0:
            for tname in (NODE_METRICS_TABLE, REGION_HEAT_TABLE):
                deleted += self._sweep_table(tname, now_ms - keep_ms)
        trace_keep_ms = trace_store.retention_ms()
        if trace_keep_ms > 0:
            deleted += self._sweep_table(trace_store.TRACE_SPANS_TABLE,
                                         now_ms - trace_keep_ms)
        from ..common import profiler
        prof_keep_ms = profiler.retention_ms()
        if prof_keep_ms > 0:
            deleted += self._sweep_table(
                profiler.PROFILE_SAMPLES_TABLE, now_ms - prof_keep_ms)
        if deleted:
            logger.info("self-monitor: retention swept %d row(s)",
                        deleted)
        return deleted

    def _sweep_table(self, tname: str, cutoff: int) -> int:
        """Batched key-scan + delete of one system table's expired rows
        (at most SWEEP_BATCH_ROWS per tick — backlogs drain tick by
        tick instead of materializing inside the scrape lock)."""
        from .. import DEFAULT_CATALOG_NAME
        from ..common.time import TimestampRange
        table = self.catalog.table(DEFAULT_CATALOG_NAME,
                                   PRIVATE_SCHEMA, tname)
        if table is None:
            return 0
        schema = table.schema
        tc = schema.timestamp_column
        key_cols = schema.tag_names() + [tc.name]
        old: Dict[str, list] = {c: [] for c in key_cols}
        budget = self.SWEEP_BATCH_ROWS
        for b in table.scan_batches(
                projection=key_cols,
                time_range=TimestampRange(None, cutoff)):
            d = b.to_pydict()
            take = min(budget, len(d[tc.name]))
            for c in key_cols:
                old[c].extend(d[c][:take])
            budget -= take
            if budget <= 0:
                break
        n = len(old[tc.name])
        if n:
            table.delete(old)
        return n

    # ---- introspection (information_schema.self_monitor) ----
    def row(self) -> Dict[str, object]:
        return {
            "node": self.node_label,
            "ticks": int(self.stats["ticks"]),
            "metric_rows": int(self.stats["metric_rows"]),
            "heat_rows": int(self.stats["heat_rows"]),
            "rows_written": int(self.stats["rows_written"]),
            "retention_deleted": int(self.stats["retention_deleted"]),
            "retention_ms": retention_ms(),
            "last_tick_ms": float(self.stats["last_tick_ms"]),
            "last_error": self.stats["last_error"],
        }
