"""Self-monitoring: the database is its own monitoring store.

Reference behavior: GreptimeDB's `export_metrics` option ("export
metrics to self") — a per-node task periodically writes the process'
own Prometheus registry into ordinary time-series tables, so the
cluster's history is queryable with SQL/PromQL, rollable-up with flows,
and subject to the same retention/compaction as user data.
"""

from .scraper import (NODE_METRICS_TABLE, PRIVATE_SCHEMA,
                      REGION_HEAT_TABLE, SelfMonitor)

__all__ = ["SelfMonitor", "PRIVATE_SCHEMA", "NODE_METRICS_TABLE",
           "REGION_HEAT_TABLE"]
